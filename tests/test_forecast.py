"""Predictive-telemetry suite (ISSUE 8, docs/forecast.md).

Covers the whole forecasting layer:

  * EWMA/Holt kernel device<->host parity (byte-exact arrays, >= 25
    randomized histories incl. missing samples and constant series) and
    its fit behavior;
  * the history tensor staging: view alignment, right-aligned ragged
    series, the int32 de-scale for huge metrics;
  * the Forecaster engine: refit-on-generation memoization, the widening
    horizon through staleness, host/native predicted values agreeing;
  * the ACCEPTANCE invariant through the REAL verbs on BOTH front-ends:
    scheduleonmetric rankings on forecasts are byte-comparable
    native<->host and across front-ends, and genuinely differ from
    snapshot rankings on a trending cluster;
  * trend-aware hysteresis: transient spikes with negative slope hold
    drift streaks (suppressed-eviction counter) while real trends
    escalate unchanged;
  * degraded LKG mode's bounded extrapolation: forecasts serve past the
    frozen-LKG window while the band holds, then the pre-forecast
    fallback returns;
  * /debug/forecast 200/404/405 on both front-ends + the /debug index;
  * the gang-mode Filter response cache restore: non-gang pods hit the
    cache keyed on the reservation version, gang members still bypass.
"""

import json

import numpy as np
import pytest

from benchmarks.forecast_load import spike_ab, trending_ab
from benchmarks.gang_load import _gang_pod_obj, build_mesh_service
from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.forecast import Forecaster
from platform_aware_scheduling_tpu.ops import forecast as ops_forecast
from platform_aware_scheduling_tpu.ops.state import (
    TensorStateMirror,
    build_history_tensor,
)
from platform_aware_scheduling_tpu.rebalance.drift import DriftDetector
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas import degraded as degraded_mode
from platform_aware_scheduling_tpu.tas.degraded import DegradedModeController
from platform_aware_scheduling_tpu.tas.metrics import (
    DummyMetricsClient,
    NodeMetric,
)
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.faults import FakeClock
from platform_aware_scheduling_tpu.utils import labels as shared_labels
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.quantity import Quantity
from platform_aware_scheduling_tpu.utils.tracing import CounterSet
from wirehelpers import get_request, post_bytes, raw_request, start_async, \
    start_threaded


# ---------------------------------------------------------------------------
# kernel parity + behavior
# ---------------------------------------------------------------------------


class TestForecastKernel:
    def test_device_host_parity_byte_exact(self):
        """ACCEPTANCE: >= 25 randomized histories — missing samples,
        constant series, full masks — byte-exact device<->host."""
        rng = np.random.default_rng(11)
        for case in range(30):
            m = int(rng.integers(1, 6))
            n = int(rng.integers(1, 10))
            w = int(rng.integers(1, 40))
            values = rng.integers(
                -(2**30), 2**30, size=(m, n, w)
            ).astype(np.int32)
            valid = rng.random((m, n, w)) < 0.7
            if case % 5 == 0:
                values[:] = 54321  # constant series
            if case % 7 == 0:
                valid[:] = True  # dense
            if case % 11 == 0:
                valid[:] = False  # fully missing
            horizon = int(rng.integers(1, 8))
            device = ops_forecast.forecast_device(values, valid, horizon)
            host = ops_forecast.forecast_host(values, valid, horizon)
            for name, d_arr, h_arr in zip(device._fields, device, host):
                assert d_arr.dtype == h_arr.dtype, (case, name)
                assert np.array_equal(d_arr, h_arr), (case, name)

    def test_constant_series_is_flat_certainty(self):
        values = np.full((1, 1, 16), 5000, np.int32)
        fit = ops_forecast.forecast_host(
            values, np.ones((1, 1, 16), bool), 3
        )
        assert fit.level[0, 0] == 5000
        assert fit.trend[0, 0] == 0
        assert fit.predicted[0, 0] == 5000
        assert fit.band[0, 0] == 0  # zero residual -> zero uncertainty

    def test_linear_ramp_tracks_slope_and_extrapolates(self):
        w = 16
        values = (np.arange(w, dtype=np.int32) * 1000).reshape(1, 1, w)
        fit = ops_forecast.forecast_host(values, np.ones((1, 1, w), bool), 1)
        # the Holt trend converges near the true 1000/step slope and the
        # prediction lands near the next sample (16000)
        assert 900 <= fit.trend[0, 0] <= 1100
        assert 15_500 <= fit.predicted[0, 0] <= 16_500
        assert fit.band[0, 0] > 0  # nonzero residual during convergence

    def test_missing_samples_never_update_state(self):
        values = np.full((1, 1, 8), 7777, np.int32)
        valid = np.zeros((1, 1, 8), bool)
        fit = ops_forecast.forecast_host(values, valid, 1)
        assert fit.samples[0, 0] == 0
        assert fit.level[0, 0] == 0 and fit.predicted[0, 0] == 0
        # a single valid sample seeds the level with zero trend
        valid[0, 0, 3] = True
        fit = ops_forecast.forecast_host(values, valid, 5)
        assert fit.samples[0, 0] == 1
        assert fit.level[0, 0] == 7777
        assert fit.trend[0, 0] == 0
        assert fit.predicted[0, 0] == 7777

    def test_residual_accumulator_headroom_on_noisy_ceiling_series(self):
        """REVIEW: the staging bit budget is WINDOW-AWARE — `acc` sums up
        to W-1 absolute errors, so a full-window noisy series de-scaled
        to the per-step ceiling alone would wrap `acc` negative in int32
        (garbage resid/band on BOTH paths identically)."""
        from platform_aware_scheduling_tpu.ops.state import (
            history_value_bits,
        )

        w = 32
        bits = history_value_bits(w)
        assert bits <= 30 - 1 - (w - 1).bit_length()
        rng = np.random.default_rng(7)
        # a worst-case series inside the budget: alternating near the
        # magnitude ceiling, so every one-step error is ~2x the range
        ceiling = (1 << bits) - 1
        values = (
            rng.integers(0, 2, size=(2, 3, w)) * 2 * ceiling - ceiling
        ).astype(np.int32)
        valid = np.ones((2, 3, w), bool)
        for fit in (
            ops_forecast.forecast_host(values, valid, 1),
            ops_forecast.forecast_device(values, valid, 1),
        ):
            assert (fit.resid >= 0).all()
            assert (fit.band >= 0).all()

    def test_band_widens_with_horizon(self):
        rng = np.random.default_rng(3)
        values = (
            1000 + rng.integers(-200, 200, size=(1, 1, 12))
        ).astype(np.int32)
        valid = np.ones((1, 1, 12), bool)
        near = ops_forecast.forecast_host(values, valid, 1)
        far = ops_forecast.forecast_host(values, valid, 9)
        assert far.band[0, 0] > near.band[0, 0]
        # extend_horizon reproduces the fresh far fit exactly
        extended = ops_forecast.extend_horizon(near, 9)
        assert np.array_equal(extended.predicted, far.predicted)
        assert np.array_equal(extended.band, far.band)


# ---------------------------------------------------------------------------
# history tensor staging
# ---------------------------------------------------------------------------


def _seeded_cache_mirror(window=8, clock=None):
    cache = (
        AutoUpdatingCache(clock=clock) if clock else AutoUpdatingCache()
    )
    cache.configure_history(window)
    mirror = TensorStateMirror()
    mirror.attach(cache)
    return cache, mirror


class TestHistoryTensor:
    def test_alignment_and_right_padding(self):
        cache, mirror = _seeded_cache_mirror(window=4)
        cache.write_metric(
            "m", {"a": NodeMetric(value=Quantity("1")),
                  "b": NodeMetric(value=Quantity("2"))}
        )
        cache.write_metric(
            "m", {"a": NodeMetric(value=Quantity("3"))}  # b missing
        )
        view = mirror.device_view()
        _gen, history = cache.history_snapshot()
        tensor = build_history_tensor(view, history, 4)
        row = view.metric_index["m"]
        col_a, col_b = view.node_index["a"], view.node_index["b"]
        # 2 samples right-aligned at slots 2, 3
        assert not tensor.valid[row, :, :2].any()
        assert tensor.values[row, col_a, 2] == 1000
        assert tensor.values[row, col_a, 3] == 3000
        assert tensor.valid[row, col_b, 2]
        assert not tensor.valid[row, col_b, 3]  # the gap stays visible
        assert tensor.shift[row] == 0

    def test_huge_values_descale_into_int32(self):
        cache, mirror = _seeded_cache_mirror(window=4)
        big = 10**15  # ~2^50 milli: far past int32
        cache.write_metric(
            "mem", {"a": NodeMetric(value=Quantity(str(big)))}
        )
        view = mirror.device_view()
        _gen, history = cache.history_snapshot()
        tensor = build_history_tensor(view, history, 4)
        row = view.metric_index["mem"]
        shift = int(tensor.shift[row])
        assert shift > 0
        col = view.node_index["a"]
        staged = int(tensor.values[row, col, 3])
        assert abs(staged) < 2**31
        # unscaling recovers the value to within the dropped low bits
        assert abs((staged << shift) - big * 1000) < (1 << shift)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


class TestForecasterEngine:
    def _trending(self, steps=6, clock=None, **kwargs):
        cache, mirror = _seeded_cache_mirror(window=8, clock=clock)
        if clock is not None:
            kwargs["clock"] = clock.now
        forecaster = Forecaster(
            cache, mirror, window=8, period_s=1.0, **kwargs
        )
        for step in range(steps):
            cache.write_metric(
                "cpu",
                {
                    "riser": NodeMetric(value=Quantity(100 + step * 300)),
                    "flat": NodeMetric(value=Quantity(1950)),
                },
            )
        forecaster.refresh()
        return cache, mirror, forecaster

    def test_refit_memoized_per_generation(self):
        counters = CounterSet()
        cache, mirror = _seeded_cache_mirror(window=8)
        forecaster = Forecaster(
            cache, mirror, window=8, period_s=1.0, counters=counters
        )
        cache.write_metric("cpu", {"n": NodeMetric(value=Quantity("5"))})
        forecaster.refresh()
        assert counters.get("pas_forecast_fit_passes_total") == 1
        forecaster.refresh()  # no history movement -> no refit
        assert counters.get("pas_forecast_fit_passes_total") == 1
        cache.write_metric("cpu", {"n": NodeMetric(value=Quantity("6"))})
        forecaster.refresh()
        assert counters.get("pas_forecast_fit_passes_total") == 2

    def test_ranking_view_none_without_history(self):
        cache, mirror = _seeded_cache_mirror()
        forecaster = Forecaster(cache, mirror, window=8, period_s=1.0)
        assert forecaster.ranking_view("cpu") is None

    def test_predictions_exceed_snapshot_on_uptrend(self):
        _cache, _mirror, forecaster = self._trending()
        fit = forecaster.ensure_current()
        row = fit.rows["cpu"]
        col = fit.fview.node_index["riser"]
        # last sample 1600; prediction continues the +300 trend
        assert int(fit.predicted[row, col]) > 1_600_000
        assert forecaster.trend_milli("cpu", "riser") > 0
        assert forecaster.trend_milli("cpu", "flat") == 0
        described = forecaster.describe("cpu", "riser")
        assert described.startswith("predicted cpu=")
        assert "slope +" in described and described.endswith("/s)")

    def test_horizon_widens_with_staleness(self):
        clock = FakeClock()
        _cache, _mirror, forecaster = self._trending(clock=clock)
        fit = forecaster.ensure_current()
        assert fit.horizon_steps == 1
        band_fresh = int(fit.band[fit.rows["cpu"]].max())
        clock.advance(5.0)  # five silent periods
        fit = forecaster.ensure_current()
        assert fit.horizon_steps == 6
        assert int(fit.band[fit.rows["cpu"]].max()) > band_fresh

    def test_successive_extensions_grow_linearly(self):
        """REVIEW: the horizon is anchored on the BASE horizon plus
        elapsed periods, never on an already-extended fit — one
        ensure_current per silent period must yield 2, 3, 4, ... steps,
        not the compounding 2, 4, 7, ... re-adding elapsed periods to the
        previous extension would produce."""
        clock = FakeClock()
        _cache, _mirror, forecaster = self._trending(clock=clock)
        assert forecaster.ensure_current().horizon_steps == 1
        for expected in (2, 3, 4, 5):
            clock.advance(1.0)
            fit = forecaster.ensure_current()
            assert fit.horizon_steps == expected
        # and the extended predictions stay exact: equal to a fresh
        # re-extrapolation of the stored fit at the same horizon
        manual = ops_forecast.extend_horizon(fit.scaled, 5)
        shift = fit.shift[:, None]
        assert np.array_equal(
            fit.predicted, manual.predicted.astype(np.int64) << shift
        )
        assert np.array_equal(
            fit.band, manual.band.astype(np.int64) << shift
        )

    def test_configured_horizon_capped_at_window(self):
        """REVIEW: an unbounded --forecastHorizon would feed the int32
        kernel tails (trend*h, resid*(1+h)) a wrap-scale h — the base
        horizon caps at the lookback window (no fit predicts further
        ahead than it looked back)."""
        _cache, _mirror, forecaster = self._trending(horizon_s=100_000.0)
        fit = forecaster.ensure_current()
        assert fit.horizon_steps == 8  # window, not 100k steps
        assert (fit.band >= 0).all()

    def test_host_only_metric_never_forecasts(self):
        """REVIEW: host-only metrics are host-only precisely because
        their values are not milli-exact — the milli-truncated history
        must never replace the exact-Quantity host ranking."""
        from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import (
            TASPolicyRule,
        )

        cache, mirror = _seeded_cache_mirror(window=8)
        # sub-milli values: milli_value_exact is inexact -> the mirror
        # marks the metric host-only
        for step in range(3):
            cache.write_metric(
                "submilli",
                {
                    "a": NodeMetric(value=Quantity("0.0004")),
                    "b": NodeMetric(value=Quantity("0.0006")),
                },
            )
        assert mirror.metric_host_only("submilli")
        ext = MetricsExtender(cache, mirror=mirror)

        class MustNotForecast:
            def host_metric(self, name):
                raise AssertionError("host-only metric consulted forecast")

        ext.forecaster = MustNotForecast()
        rule = TASPolicyRule(
            metricname="submilli", operator="GreaterThan", target=0
        )
        ranked = ext._prioritize_host(rule, ["a", "b"])
        # exact Quantity ordering: 0.0006 > 0.0004 (milli-truncated both
        # read 0 and would tie on dict order)
        assert [p.host for p in ranked] == ["b", "a"]

    def test_ranking_falls_back_to_snapshot_past_window(self):
        """REVIEW: assemblies WITHOUT a DegradedModeController must not
        rank on unboundedly diverging extrapolations — once staleness has
        grown the horizon a full lookback window past its base,
        ranking_view AND host_metric fall back (None -> snapshot), and
        the horizon itself clamps instead of growing toward int32 wrap."""
        clock = FakeClock()
        _cache, _mirror, forecaster = self._trending(clock=clock)
        # window=8, base horizon 1: stale but within base + window
        clock.advance(8.0)
        assert forecaster.ensure_current().horizon_steps == 9
        assert forecaster.ranking_view("cpu") is not None
        assert forecaster.host_metric("cpu") is not None
        # one more silent period crosses the gate: both paths fall back
        # TOGETHER (native<->host parity holds through the fallback)
        clock.advance(1.0)
        assert forecaster.ranking_view("cpu") is None
        assert forecaster.host_metric("cpu") is None
        # a month of staleness: the horizon is clamped one past the
        # outermost gate, far from int32 territory
        clock.advance(2_600_000.0)
        assert forecaster.ensure_current().horizon_steps == 10

    def test_host_metric_matches_forecast_view(self):
        _cache, _mirror, forecaster = self._trending()
        fit = forecaster.ensure_current()
        info = forecaster.host_metric("cpu")
        row = fit.rows["cpu"]
        for node, metric in info.items():
            col = fit.fview.node_index[node]
            milli, exact = metric.value.milli_value_exact()
            assert exact
            assert milli == int(fit.predicted[row, col])


# ---------------------------------------------------------------------------
# ranking through the real verbs — the acceptance invariant
# ---------------------------------------------------------------------------


def _forecast_extender(num_nodes=12, trending=True):
    """A device extender over load-pol whose history makes node 0 the
    lowest-now-but-rising series (build_extender's universe + a scripted
    trend), plus its Forecaster."""
    ext, names = build_extender(num_nodes, device=True)
    forecaster = Forecaster(ext.cache, ext.mirror, window=8, period_s=300.0)
    for step in range(7):
        ext.cache.write_metric(
            "load_metric",
            {
                n: NodeMetric(
                    value=Quantity(
                        100 + step * 300 if (i == 0 and trending) else 1950
                    )
                )
                for i, n in enumerate(names)
            },
        )
    forecaster.refresh()
    ext.forecaster = forecaster
    ext.warm_fastpath()
    return ext, names


def _post(ext, verb, body):
    return getattr(ext, verb)(
        HTTPRequest(
            method="POST",
            path=f"/scheduler/{verb}",
            headers={"Content-Type": "application/json"},
            body=body,
        )
    )


class TestForecastRankingVerbs:
    def test_native_and_host_rankings_byte_equal(self):
        """ACCEPTANCE: the native fastpath and the exact host strategy
        path rank on the same predicted values — byte-identical wire
        responses."""
        ext, names = _forecast_extender()
        body = make_bodies(names, "nodenames", count=1)[0]
        native = _post(ext, "prioritize", body)
        assert native.status == 200
        # force the exact host path on a fresh-but-identical extender
        ext2, names2 = _forecast_extender()
        ext2._device_prioritize_ok = lambda *a, **k: False
        host = _post(ext2, "prioritize", body)
        assert host.status == 200
        assert native.body == host.body
        # and the full-Nodes wire mode agrees too
        nodes_body = make_bodies(names, "nodes", count=1)[0]
        assert _post(ext, "prioritize", nodes_body).body == _post(
            ext2, "prioritize", nodes_body
        ).body

    def test_forecast_ranking_differs_from_snapshot(self):
        ext, names = _forecast_extender()
        body = make_bodies(names, "nodenames", count=1)[0]
        with_forecast = json.loads(_post(ext, "prioritize", body).body)
        ext.forecaster = None  # snapshot ranking
        snapshot = json.loads(_post(ext, "prioritize", body).body)
        top_forecast = max(with_forecast, key=lambda e: e["Score"])["Host"]
        top_snapshot = max(snapshot, key=lambda e: e["Score"])["Host"]
        # GreaterThan policy prefers HIGH values: the riser's predicted
        # value tops the forecast ranking while the snapshot still sees
        # it below the flat nodes
        assert top_forecast == names[0]
        assert top_snapshot != names[0]

    def test_both_front_ends_serve_identical_forecast_rankings(self):
        """ACCEPTANCE: the same forecast ranking over real sockets on the
        threaded AND async front-ends."""
        ext, names = _forecast_extender()
        body = make_bodies(names, "nodenames", count=1)[0]
        payload = post_bytes("/scheduler/prioritize", body)
        threaded = start_threaded(ext)
        try:
            status, _headers, threaded_body = raw_request(
                threaded.port, payload
            )
            assert status == 200
        finally:
            threaded.shutdown()
        ext2, _names = _forecast_extender()
        async_server = start_async(ext2)
        try:
            status, _headers, async_body = raw_request(
                async_server.port, payload
            )
            assert status == 200
        finally:
            async_server.shutdown()
        assert threaded_body == async_body
        ranked = json.loads(threaded_body)
        assert max(ranked, key=lambda e: e["Score"])["Host"] == names[0]

    def test_decision_records_carry_forecast_provenance(self):
        from platform_aware_scheduling_tpu.utils import decisions

        decisions.DECISIONS.configure(enabled=True, capacity=64)
        try:
            ext, names = _forecast_extender()
            body = make_bodies(names, "nodenames", count=1)[0]
            _post(ext, "prioritize", body)
            snap = decisions.DECISIONS.snapshot(verb="prioritize", limit=1)
            record = snap["records"][0]
            assert record["detail"]["ranking"] == "forecast"
            assert record["detail"]["top"].startswith(
                "predicted load_metric="
            )
            assert "slope" in record["detail"]["top"]
        finally:
            decisions.DECISIONS.configure(enabled=True, capacity=512)

    def test_forecast_off_path_unchanged(self):
        """--forecast=off (forecaster None) serves byte-identically to an
        extender built without any forecast plumbing."""
        ext, names = build_extender(12, device=True)
        body = make_bodies(names, "nodenames", count=1)[0]
        baseline = _post(ext, "prioritize", body).body
        ext.forecaster = None
        assert _post(ext, "prioritize", body).body == baseline


# ---------------------------------------------------------------------------
# trend-aware hysteresis
# ---------------------------------------------------------------------------


class TestTrendHysteresis:
    def test_drift_hold_semantics(self):
        drift = DriftDetector(k=2)
        violations = {"hot": ["pol"]}
        # held from the start: the streak never advances
        assert drift.observe(violations, hold=frozenset({"hot"})) == {}
        assert drift.streaks()["hot"] == 0
        assert drift.observe(violations, hold=frozenset({"hot"})) == {}
        # the hold lifts (trend flipped up): escalation resumes
        assert drift.observe(violations) == {}
        assert drift.streaks()["hot"] == 1
        assert drift.observe(violations) == {"hot": ["pol"]}
        # REVIEW: a node already AT the threshold (its eviction deferred)
        # that starts trending down is not a candidate while held — the
        # hold blocks candidacy outright, not just streak advancement
        assert drift.streaks()["hot"] == 2
        assert drift.observe(violations, hold=frozenset({"hot"})) == {}
        assert drift.streaks()["hot"] == 2  # frozen, not reset
        # hold lifts while still violating: candidacy resumes at once
        assert drift.observe(violations) == {"hot": ["pol"]}
        # recovery still resets immediately
        assert drift.observe({}) == {}
        assert drift.streaks() == {}

    def test_spike_suppression_end_to_end(self):
        """ACCEPTANCE: the transient-spike A/B through the real
        enforcement -> drift -> rebalance loop — snapshot mode evicts,
        forecast mode suppresses every eviction and still converges."""
        result = spike_ab()
        assert result["snapshot"]["evictions"] >= 1
        assert result["forecast"]["evictions"] == 0
        assert result["forecast"]["suppressed"] >= 1
        # both end clean: the spike resolves either way — forecast just
        # got there without destroying work
        assert result["forecast"]["final_violations"] == 0
        assert result["snapshot"]["final_violations"] == 0

    def test_suppression_counted_once_per_spike(self):
        """REVIEW: a held node's streak STAYS at k-1, so it re-satisfies
        the would-have-evicted test every cycle of the spike — one spike
        must count ONE suppressed eviction, however long it lasts; a
        fresh spike after recovery counts again."""
        from platform_aware_scheduling_tpu.rebalance.loop import Rebalancer

        class CountingForecaster:
            suppressed = 0

            def count_suppressed_eviction(self, n=1):
                self.suppressed += n

        rebalancer = Rebalancer(None, None, hysteresis_cycles=2)
        counting = CountingForecaster()
        rebalancer.forecaster = counting
        rebalancer._trend_holds = lambda violations: frozenset(violations)
        violations = {"hot": ["pol"]}
        rebalancer.cycle(violations)  # streak would reach 1: below k
        assert counting.suppressed == 0
        rebalancer.drift._streaks["hot"] = 1  # next advance would evict
        for _ in range(4):  # a four-cycle spike, held at k-1 throughout
            rebalancer.cycle(violations)
        assert counting.suppressed == 1
        rebalancer._trend_holds = lambda violations: frozenset()
        rebalancer.cycle({})  # spike resolves: streak + counted set clear
        rebalancer._trend_holds = lambda violations: frozenset(violations)
        rebalancer.drift._streaks["hot"] = 1
        rebalancer.cycle(violations)  # a NEW spike: one more
        assert counting.suppressed == 2
        # REVIEW: a node held at/past the threshold (deferred eviction,
        # now resolving) is both blocked from candidacy and counted
        rebalancer._trend_holds = lambda violations: frozenset()
        rebalancer.cycle({})
        rebalancer.drift._streaks["late"] = 3  # already past k=2
        rebalancer._trend_holds = lambda violations: frozenset(violations)
        record = rebalancer.cycle({"late": ["pol"]})
        assert record["candidate_nodes"] == []
        assert counting.suppressed == 3

    def test_trending_up_violation_still_escalates(self):
        """A genuine trend must evict exactly as before: rising series
        never hold streaks."""
        cache, mirror = _seeded_cache_mirror(window=8)
        forecaster = Forecaster(cache, mirror, window=8, period_s=1.0)
        for step in range(4):
            cache.write_metric(
                "load",
                {"hot": NodeMetric(value=Quantity(2000 + step * 100))},
            )
        forecaster.refresh()
        assert forecaster.trending_down("hot", ["load"]) is False

    def test_trending_ab_reduces_violated_at_bind(self):
        """ACCEPTANCE: forecast-on strictly reduces violated-at-bind
        placements on the trending scenario."""
        result = trending_ab(num_nodes=6, pods=4)
        assert (
            result["forecast"]["violated_at_bind"]
            < result["snapshot"]["violated_at_bind"]
        )
        assert result["forecast"]["violated_at_bind"] == 0
        assert result["snapshot"]["chose_riser"] == 4


# ---------------------------------------------------------------------------
# degraded bounded extrapolation
# ---------------------------------------------------------------------------


class TestDegradedExtrapolation:
    def _stale_setup(
        self, noisy: bool, band_bound: float = 0.25, window: int = 64
    ):
        # forecaster window 64 >> the 8 samples written: these tests
        # probe the BAND bound at 20ish-period staleness, which must stay
        # inside the horizon-vs-window cap (its own test below)
        clock = FakeClock()
        cache, mirror = _seeded_cache_mirror(window=8, clock=clock)
        cache._refresh_period = 1.0
        cache.write_metric("cpu")  # register for the refresh loop
        forecaster = Forecaster(
            cache, mirror, window=window, period_s=1.0,
            band_bound=band_bound, clock=clock.now,
        )
        rng = np.random.default_rng(5)
        client_values = []
        for step in range(8):
            noise = int(rng.integers(-400, 400)) if noisy else 0
            client_values.append(1000 + noise)
        for value in client_values:
            clock.advance(1.0)
            cache.update_all_metrics(
                DummyMetricsClient(
                    {"cpu": {"n": NodeMetric(value=Quantity(value))}}
                )
            )
        controller = DegradedModeController(
            cache, mode=degraded_mode.MODE_LAST_KNOWN_GOOD,
            counters=CounterSet(),
        )
        controller.forecaster = forecaster
        return clock, cache, controller, forecaster

    def test_extrapolation_extends_lkg_window(self):
        clock, cache, controller, forecaster = self._stale_setup(noisy=False)
        action, _ = controller.prioritize_decision()
        assert action == degraded_mode.ACTION_NORMAL
        # stale past the frozen-LKG window (bound 3s x multiple 3 = 9s):
        # pre-forecast behavior was NEUTRAL; a zero-residual forecast
        # extrapolates with a zero-width band -> keeps serving LKG scores
        clock.advance(20.0)
        action, reason = controller.prioritize_decision()
        assert action == degraded_mode.ACTION_LAST_KNOWN_GOOD
        assert "extrapolating" in reason
        filter_action, filter_reason = controller.filter_decision()
        assert filter_action == degraded_mode.ACTION_LAST_KNOWN_GOOD
        assert "extrapolating" in filter_reason
        assert (
            forecaster.counters.get(
                "pas_forecast_extrapolated_serves_total"
            )
            >= 2
        )

    def test_wide_band_falls_back_to_frozen_lkg_behavior(self):
        """A noisy series' band widens with the horizon until the bound
        trips — then today's frozen-LKG fallbacks (neutral Prioritize,
        fail-open Filter) return."""
        clock, cache, controller, forecaster = self._stale_setup(
            noisy=True, band_bound=0.1
        )
        # 20 silent periods: horizon 21 (within the 64-step cap) but the
        # noisy residual has inflated the relative band far past 0.1
        clock.advance(20.0)
        ok, reason = forecaster.extrapolation_ok()
        assert not ok and "exceeds bound" in reason
        action, _ = controller.prioritize_decision()
        assert action == degraded_mode.ACTION_NEUTRAL
        filter_action, _ = controller.filter_decision()
        assert filter_action == degraded_mode.ACTION_FAIL_OPEN

    def test_horizon_past_window_trips_even_at_zero_band(self):
        """REVIEW: a zero-residual (constant) series keeps band == 0 at
        ANY horizon, so the band bound alone would extrapolate a dead
        telemetry source forever.  The lookback-window cap makes "a long
        enough outage always trips back" unconditional."""
        clock, cache, controller, forecaster = self._stale_setup(
            noisy=False, window=16
        )
        clock.advance(10.0)  # horizon 11 <= 16: still serving
        ok, _ = forecaster.extrapolation_ok()
        assert ok
        action, _ = controller.prioritize_decision()
        assert action == degraded_mode.ACTION_LAST_KNOWN_GOOD
        clock.advance(10.0)  # horizon 21 > 16: cap trips, band still 0
        ok, reason = forecaster.extrapolation_ok()
        assert not ok and "lookback window" in reason
        action, _ = controller.prioritize_decision()
        assert action == degraded_mode.ACTION_NEUTRAL
        filter_action, _ = controller.filter_decision()
        assert filter_action == degraded_mode.ACTION_FAIL_OPEN

    def test_evictions_stay_suspended_while_extrapolating(self):
        """Extrapolation serves VERBS only: the unconditional eviction
        suspension is untouched."""
        clock, cache, controller, _forecaster = self._stale_setup(
            noisy=False
        )
        clock.advance(20.0)
        action, _ = controller.prioritize_decision()
        assert action == degraded_mode.ACTION_LAST_KNOWN_GOOD
        allowed, reason = controller.evictions_allowed()
        assert not allowed and "suspended" in reason


# ---------------------------------------------------------------------------
# /debug/forecast on both front-ends
# ---------------------------------------------------------------------------


class TestDebugForecast:
    def test_threaded_and_async_endpoints(self):
        ext, _names = _forecast_extender()
        for start in (start_threaded, start_async):
            server = start(ext)
            try:
                status, _headers, body = get_request(
                    server.port, "/debug/forecast"
                )
                assert status == 200
                payload = json.loads(body)
                assert payload["enabled"] is True
                assert payload["fitted"] is True
                assert "load_metric" in payload["metrics"]
                status, _headers, body = get_request(server.port, "/debug")
                paths = [
                    e["path"]
                    for e in json.loads(body)["endpoints"]
                ]
                assert "/debug/forecast" in paths
            finally:
                server.shutdown()

    def test_404_when_off_and_405_non_get(self):
        ext, _names = build_extender(8, device=True)
        server = start_threaded(ext)
        try:
            status, _headers, _body = get_request(
                server.port, "/debug/forecast"
            )
            assert status == 404
        finally:
            server.shutdown()
        ext2, _names = _forecast_extender()
        server = start_threaded(ext2)
        try:
            status, _headers, _body = raw_request(
                server.port, post_bytes("/debug/forecast", b"{}")
            )
            assert status == 405
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# flags + assembly
# ---------------------------------------------------------------------------


class TestFlagsAndAssembly:
    def test_tas_has_forecast_flags_gas_does_not(self):
        from platform_aware_scheduling_tpu.cmd import gas, tas

        tas_args = tas.build_arg_parser().parse_args([])
        assert tas_args.forecast == "off"
        assert tas_args.forecastWindow == 32
        gas_parser = gas.build_arg_parser()
        with pytest.raises(SystemExit):
            gas_parser.parse_args(["--forecast", "on"])

    def test_forecast_options_off_is_none(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        args = tas.build_arg_parser().parse_args([])
        assert common.forecast_options(args, 5.0) is None
        args = tas.build_arg_parser().parse_args(
            ["--forecast", "on", "--forecastHorizon", "10s"]
        )
        options = common.forecast_options(args, 5.0)
        assert options["window"] == 32
        assert options["horizon_s"] == 10.0
        assert options["period_s"] == 5.0

    def test_assemble_wires_forecaster_everywhere(self):
        from platform_aware_scheduling_tpu.cmd import tas
        from platform_aware_scheduling_tpu.testing.fake_kube import (
            FakeKubeClient,
        )

        fake = FakeKubeClient()
        client = DummyMetricsClient({})
        cache, mirror, extender, _controller, enforcer, stop = tas.assemble(
            fake,
            client,
            sync_period_s=3600.0,
            rebalance_mode="dry-run",
            degraded_mode="last-known-good",
            forecast_options={"window": 8, "period_s": 3600.0},
        )
        try:
            assert extender.forecaster is not None
            assert extender.degraded.forecaster is extender.forecaster
            assert (
                extender.rebalancer.forecaster is extender.forecaster
            )
            # the cache history records at the configured window
            assert cache.history_window() == 8
            # REVIEW: the post-refit ranking warm is registered AFTER the
            # forecaster's own refit hook — warm_fastpath fires mid-pass,
            # before the refit, so without this ordering every fresh
            # forecast view would go cold to its first request
            hooks = cache.on_refresh_pass
            assert extender.warm_forecast_rankings in hooks
            assert hooks.index(extender.forecaster.refresh) < hooks.index(
                extender.warm_forecast_rankings
            )
        finally:
            stop.set()

    def test_host_only_assembly_disables_forecaster(self):
        from platform_aware_scheduling_tpu.cmd import common

        assert common.build_forecaster(
            AutoUpdatingCache(), None, {"window": 8}
        ) is None


# ---------------------------------------------------------------------------
# gang-mode Filter response cache restore (satellite)
# ---------------------------------------------------------------------------


def _plain_pod_body(names, name="plain"):
    return json.dumps(
        {
            "Pod": {
                "metadata": {
                    "name": name,
                    "namespace": "default",
                    "labels": {"telemetry-policy": "gang-pol"},
                }
            },
            "NodeNames": names,
        }
    ).encode()


def _counter(name):
    return trace.COUNTERS.get(name, kind="counter")


class TestGangFilterCacheRestore:
    def test_non_gang_pods_regain_cache_hits(self):
        """ISSUE 8 satellite pin: with gang mode ON, plain pods hit the
        Filter response cache again (hit/miss counters move) instead of
        bypassing every request."""
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        body = _plain_pod_body(names)
        before_hit = _counter("pas_filter_cache_hit_total")
        before_miss = _counter("pas_filter_cache_miss_total")
        before_bypass = _counter("pas_filter_cache_bypass_total")
        first = _post(extender, "filter", body)
        second = _post(extender, "filter", body)
        assert first.status == second.status == 200
        assert first.body == second.body
        assert _counter("pas_filter_cache_miss_total") == before_miss + 1
        assert _counter("pas_filter_cache_hit_total") == before_hit + 1
        assert _counter("pas_filter_cache_bypass_total") == before_bypass

    def test_rebalance_grouped_pods_keep_cache_hits(self):
        """REVIEW: ``pas-workload-group`` alone is the rebalancer's
        min-available grouping that ordinary NON-gang workloads carry —
        gang membership needs ``pas-gang-size`` too (labels.gang_id_for).
        A grouped-but-not-gang pod must keep its cache hits, not pay the
        exact path per request."""
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        body = json.dumps(
            {
                "Pod": {
                    "metadata": {
                        "name": "grouped",
                        "namespace": "default",
                        "labels": {
                            "telemetry-policy": "gang-pol",
                            shared_labels.GROUP_LABEL: "web-tier",
                        },
                    }
                },
                "NodeNames": names,
            }
        ).encode()
        before_hit = _counter("pas_filter_cache_hit_total")
        before_bypass = _counter("pas_filter_cache_bypass_total")
        first = _post(extender, "filter", body)
        second = _post(extender, "filter", body)
        assert first.body == second.body
        assert _counter("pas_filter_cache_hit_total") == before_hit + 1
        assert _counter("pas_filter_cache_bypass_total") == before_bypass

    def test_gang_members_still_bypass(self):
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        before_bypass = _counter("pas_filter_cache_bypass_total")
        before_hit = _counter("pas_filter_cache_hit_total")
        gang_body = json.dumps(
            {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
             "NodeNames": names}
        ).encode()
        _post(extender, "filter", gang_body)
        _post(extender, "filter", gang_body)
        assert _counter("pas_filter_cache_bypass_total") == before_bypass + 2
        assert _counter("pas_filter_cache_hit_total") == before_hit

    def test_reservation_change_invalidates_cached_verdict(self):
        """A cached non-gang verdict must reflect every reservation
        change: after gang A reserves, the next plain request MISSES and
        fails A's slice with the concrete gang reason; cached bytes then
        hit again at the new version."""
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        body = _plain_pod_body(names)
        clean = _post(extender, "filter", body)
        assert json.loads(clean.body)["FailedNodes"] == {}
        hit = _post(extender, "filter", body)
        assert hit.body == clean.body
        # gang A reserves a 2x4 slice -> reservation version bumps
        _post(
            extender,
            "filter",
            json.dumps(
                {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
                 "NodeNames": names}
            ).encode(),
        )
        after = _post(extender, "filter", body)
        failed = json.loads(after.body)["FailedNodes"]
        assert len(failed) == 8
        assert all(
            "reserved by gang default/gang-a" in reason
            for reason in failed.values()
        )
        # the merged verdict is itself cacheable at the new version
        before_hit = _counter("pas_filter_cache_hit_total")
        again = _post(extender, "filter", body)
        assert again.body == after.body
        assert _counter("pas_filter_cache_hit_total") == before_hit + 1

    def test_cached_and_exact_verdicts_byte_equal(self):
        """The native cached response equals the exact path's bytes for
        the same request under active reservations."""
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        _post(
            extender,
            "filter",
            json.dumps(
                {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
                 "NodeNames": names}
            ).encode(),
        )
        body = _plain_pod_body(names)
        native = _post(extender, "filter", body)
        # identical scenario on a second service, exact path forced
        extender2, _kube2, names2 = build_mesh_service(4, 4, gang=True)
        _post(
            extender2,
            "filter",
            json.dumps(
                {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
                 "NodeNames": names2}
            ).encode(),
        )
        extender2.fastpath = None  # no probe: exact path owns the verdict
        exact = _post(extender2, "filter", body)
        assert native.body == exact.body

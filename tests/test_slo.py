"""SLO engine suite (utils/slo.py; docs/observability.md "SLOs & error
budgets"): bucket-quantile interpolation + the sub-millisecond histogram
bounds, SLI measurement per kind, multi-window burn-rate alerting on
fake clocks, /debug/slo + the /debug index completeness gate on both
front-ends, and the --slo=off off-path pins (zero gauges, byte-identical
wire)."""

import json

import pytest

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import (
    DEBUG_ENDPOINTS,
    EXECUTOR_DEBUG_PATHS,
    HTTPRequest,
    QUEUE_BYPASS_PATHS,
    Server,
)
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.slo import (
    ALERT_OK,
    ALERT_PAGE,
    ALERT_WARN,
    SLO,
    SLOEngine,
    default_slos,
    merge_config,
    slo_from_dict,
)
from platform_aware_scheduling_tpu.utils.tracing import (
    _BUCKETS,
    CounterSet,
    LatencyRecorder,
    bucket_count_below,
    histograms_text,
    quantile_from_buckets,
)
from wirehelpers import get_request, post_bytes, raw_request, start_async, start_threaded


def _buckets(**at):
    """A per-bucket count array from {bound_index: count} (+Inf = -1)."""
    out = [0] * (len(_BUCKETS) + 1)
    for idx, count in at.items():
        out[int(idx)] = count
    return out


class TestBucketQuantiles:
    """Satellite: quantile-from-buckets must interpolate within the
    bucket, with the edge cases pinned."""

    def test_zero_observations_is_zero(self):
        assert quantile_from_buckets([0] * (len(_BUCKETS) + 1), 0.99) == 0.0

    def test_single_bucket_interpolates_inside(self):
        # 10 samples all in the (0.0002, 0.00025] bucket (index 2): the
        # median estimate must land INSIDE the bucket, not on its edge
        buckets = _buckets(**{"2": 10})
        p50 = quantile_from_buckets(buckets, 0.50)
        assert _BUCKETS[1] < p50 < _BUCKETS[2]

    def test_all_in_inf_returns_last_finite_bound(self):
        buckets = [0] * (len(_BUCKETS) + 1)
        buckets[-1] = 7
        assert quantile_from_buckets(buckets, 0.5) == _BUCKETS[-1]
        assert quantile_from_buckets(buckets, 0.99) == _BUCKETS[-1]

    def test_interpolation_matches_uniform_assumption(self):
        # 100 samples in the first bucket (0, 0.0001]: p50 -> ~50 µs
        buckets = _buckets(**{"0": 100})
        assert quantile_from_buckets(buckets, 0.50) == pytest.approx(
            0.00005, rel=0.05
        )

    def test_sparse_buckets_skip_empties(self):
        # 1 sample in bucket 0, 1 in bucket 8: p99 targets the second —
        # interpolated within ITS bounds, ignoring the empty gap
        buckets = _buckets(**{"0": 1, "8": 1})
        p99 = quantile_from_buckets(buckets, 0.99)
        assert _BUCKETS[7] < p99 <= _BUCKETS[8]

    def test_count_below_whole_and_fractional(self):
        # bucket 0 fully under 1 ms; bucket index of 0.0016 straddles a
        # 1.2 ms threshold: fractional credit, linear within the bucket
        i_16 = _BUCKETS.index(0.0016)
        buckets = _buckets(**{"0": 4, str(i_16): 10})
        lower = _BUCKETS[i_16 - 1]  # 0.0008
        expected = 4 + 10 * (0.0012 - lower) / (0.0016 - lower)
        assert bucket_count_below(buckets, 0.0012) == pytest.approx(expected)
        # +Inf samples never count below any finite threshold
        buckets[-1] = 5
        assert bucket_count_below(buckets, 10_000.0) == pytest.approx(14.0)


class TestSubMillisecondBounds:
    """Satellite: the histogram ladder resolves the sub-ms serving
    floor, and the new bounds round-trip through real exposition."""

    def test_ladder_contains_sub_ms_bounds(self):
        for bound in (0.0001, 0.0002, 0.00025, 0.0004, 0.0005, 0.00075):
            assert bound in _BUCKETS, f"{bound} missing from the ladder"
        assert _BUCKETS == sorted(set(_BUCKETS)), "ladder must be sorted"

    def test_exposition_round_trip_resolves_755us(self):
        recorder = LatencyRecorder()
        for v in (0.0003, 0.0006, 0.000755, 0.002):
            recorder.observe("prioritize", v)
        text = histograms_text([recorder])
        families = trace.parse_prometheus_text(text)
        family = families["pas_request_duration_seconds"]
        by_le = {
            labels["le"]: value
            for name, labels, value in family["samples"]
            if name.endswith("_bucket")
        }
        # the new bounds are on the wire and the ladder separates the
        # 300/600/755 µs samples instead of flattening them into 2x steps
        assert by_le["0.00025"] == 0
        assert by_le["0.0004"] == 1  # 300 µs
        assert by_le["0.00075"] == 2  # + 600 µs
        assert by_le["0.0008"] == 3  # + 755 µs
        assert by_le["+Inf"] == 4


class TestDeclarations:
    def test_slo_validation(self):
        with pytest.raises(ValueError):
            SLO(name="x", sli="nope", objective=0.9)
        with pytest.raises(ValueError):
            SLO(name="x", sli="latency", objective=1.5, verbs=("a",),
                threshold_s=0.1)
        with pytest.raises(ValueError):
            SLO(name="x", sli="latency", objective=0.9)  # no verbs
        with pytest.raises(ValueError):
            SLO(name="x", sli="availability", objective=0.9)  # no verbs
        with pytest.raises(ValueError):
            SLO(name="x", sli="counter_ratio", objective=0.9)  # no specs

    def test_slo_from_dict_rejects_unknown_keys(self):
        with pytest.raises(ValueError, match="unknown keys"):
            slo_from_dict(
                {"name": "x", "sli": "freshness", "objective": 0.9,
                 "objectiv": 0.5}
            )

    def test_slo_from_dict_missing_required_keys_is_value_error(self):
        # the documented fail-fast contract is ValueError, not a bare
        # KeyError traceback naming no entry
        with pytest.raises(ValueError, match="objective"):
            slo_from_dict({"name": "filter_p99", "threshold_ms": 5})
        with pytest.raises(ValueError, match="name"):
            slo_from_dict({"sli": "freshness", "objective": 0.9})

    def test_merge_config_replace_disable_append(self):
        base = default_slos()
        merged = merge_config(
            base,
            json.dumps(
                {
                    "slos": [
                        {"name": "filter_p99", "disabled": True},
                        {
                            "name": "prioritize_p99",
                            "sli": "latency",
                            "objective": 0.95,
                            "verbs": ["prioritize"],
                            "threshold_ms": 50,
                        },
                        {
                            "name": "custom_ratio",
                            "sli": "counter_ratio",
                            "objective": 0.9,
                            "good": ["pas_rebalance_moves_executed_total"],
                        },
                    ]
                }
            ),
        )
        names = {slo.name for slo in merged}
        assert "filter_p99" not in names
        assert "custom_ratio" in names
        prio = next(s for s in merged if s.name == "prioritize_p99")
        assert prio.objective == 0.95
        assert prio.threshold_s == pytest.approx(0.05)

    def test_merge_config_malformed_fails_fast(self):
        with pytest.raises(ValueError):
            merge_config(default_slos(), '{"slos": {"not": "a list"}}')
        with pytest.raises(ValueError):
            merge_config(default_slos(), '[{"sli": "freshness"}]')

    def test_duplicate_slo_names_rejected(self):
        slo = SLO(name="dup", sli="freshness", objective=0.9)
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine([slo, slo])

    def test_windows_must_cover_alert_tiers(self):
        with pytest.raises(ValueError, match="alert tiers"):
            SLOEngine(
                [SLO(name="f", sli="freshness", objective=0.9)],
                windows={"5m": 300.0},
            )


class _Clock:
    def __init__(self, start=1000.0):
        self.t = start

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


class TestEngineMeasurement:
    def test_latency_sli_counts_under_threshold(self):
        clock = _Clock()
        recorder = LatencyRecorder()
        engine = SLOEngine(
            [
                SLO(
                    name="lat",
                    sli="latency",
                    objective=0.5,
                    verbs=("prioritize",),
                    threshold_s=0.001,
                )
            ],
            recorders=[recorder],
            clock=clock,
        )
        engine.tick()  # baseline
        for v in (0.0002, 0.0003, 0.002, 0.004):  # 2 good, 2 bad
            recorder.observe("prioritize", v)
        clock.advance(10)
        out = engine.tick()["lat"]
        assert out["events"]["total"] == pytest.approx(4.0)
        assert out["compliance"] == pytest.approx(0.5, abs=0.01)
        assert out["p99_ms"] is not None and out["p99_ms"] > 1.0

    def test_availability_sli_counts_shed_requests(self):
        clock = _Clock()
        recorder = LatencyRecorder()
        shed = CounterSet()
        engine = SLOEngine(
            [
                SLO(
                    name="avail",
                    sli="availability",
                    objective=0.9,
                    verbs=("prioritize", "filter"),
                    bad=(("pas_serving_rejected_total", None),),
                )
            ],
            recorders=[recorder],
            counter_sets=[shed],
            clock=clock,
        )
        engine.tick()
        for _ in range(8):
            recorder.observe("prioritize", 0.001)
        shed.inc("pas_serving_rejected_total", 2)
        clock.advance(10)
        out = engine.tick()["avail"]
        assert out["events"]["total"] == pytest.approx(10.0)
        assert out["compliance"] == pytest.approx(0.8)

    def test_counter_ratio_with_labels(self):
        clock = _Clock()
        cs = CounterSet()
        engine = SLOEngine(
            [
                SLO(
                    name="evict",
                    sli="counter_ratio",
                    objective=0.9,
                    good=(("pas_rebalance_moves_executed_total", None),),
                    bad=(
                        (
                            "pas_rebalance_moves_skipped_total",
                            (("reason", "pdb"),),
                        ),
                    ),
                )
            ],
            counter_sets=[cs],
            clock=clock,
        )
        engine.tick()
        cs.inc("pas_rebalance_moves_executed_total", 9)
        cs.inc(
            "pas_rebalance_moves_skipped_total", 1,
            labels={"reason": "pdb"},
        )
        # a skip reason OUTSIDE the spec's labels must not count as bad
        cs.inc(
            "pas_rebalance_moves_skipped_total", 5,
            labels={"reason": "dry_run"},
        )
        clock.advance(10)
        out = engine.tick()["evict"]
        assert out["events"]["total"] == pytest.approx(10.0)
        assert out["compliance"] == pytest.approx(0.9)

    def test_freshness_is_time_weighted_on_the_clock(self):
        clock = _Clock()
        fresh = [True]
        engine = SLOEngine(
            [SLO(name="f", sli="freshness", objective=0.5)],
            freshness=lambda: (fresh[0], ""),
            clock=clock,
        )
        engine.tick()  # baseline (no dt yet)
        for _ in range(4):  # 40 s fresh
            clock.advance(10)
            engine.tick()
        fresh[0] = False
        for _ in range(6):  # 60 s stale
            clock.advance(10)
            engine.tick()
        out = engine.tick()["f"]
        assert out["cumulative"]["total"] == pytest.approx(100.0)
        assert out["cumulative"]["good"] == pytest.approx(40.0)

    def test_first_tick_ignores_preexisting_counter_history(self):
        clock = _Clock()
        cs = CounterSet()
        cs.inc("pas_rebalance_moves_executed_total", 3)
        cs.inc(
            "pas_rebalance_moves_skipped_total", 97,
            labels={"reason": "pdb"},
        )
        engine = SLOEngine(
            [
                SLO(
                    name="evict",
                    sli="counter_ratio",
                    objective=0.999,
                    good=(("pas_rebalance_moves_executed_total", None),),
                    bad=(
                        (
                            "pas_rebalance_moves_skipped_total",
                            (("reason", "pdb"),),
                        ),
                    ),
                )
            ],
            counter_sets=[cs],
            clock=clock,
        )
        out = engine.tick()["evict"]
        # the 97 historical bad events are NOT this engine's window
        assert out["events"]["total"] == 0.0
        assert out["compliance"] == 1.0
        assert out["alert"] == ALERT_OK

    def test_no_events_means_compliant(self):
        clock = _Clock()
        engine = SLOEngine(
            [
                SLO(
                    name="lat",
                    sli="latency",
                    objective=0.99,
                    verbs=("prioritize",),
                    threshold_s=0.001,
                )
            ],
            recorders=[LatencyRecorder()],
            clock=clock,
        )
        engine.tick()
        clock.advance(1000)
        out = engine.tick()["lat"]
        assert out["compliance"] == 1.0
        assert out["error_budget_remaining"] == 1.0
        assert all(rate == 0.0 for rate in out["burn_rate"].values())


class TestBurnRateAlerting:
    def _storm_engine(self, clock, fresh):
        return SLOEngine(
            [SLO(name="f", sli="freshness", objective=0.999)],
            freshness=lambda: (fresh[0], ""),
            clock=clock,
        )

    def test_page_fires_and_clears_with_budget_memory(self):
        clock = _Clock()
        fresh = [True]
        engine = self._storm_engine(clock, fresh)
        for _ in range(6):  # 30 s healthy
            engine.tick()
            clock.advance(5)
        fresh[0] = False
        paged_at = None
        for i in range(8):  # 40 s storm
            out = engine.tick()["f"]
            if out["alert"] == ALERT_PAGE and paged_at is None:
                paged_at = i
            clock.advance(5)
        assert paged_at is not None, "the storm must reach the page tier"
        fresh[0] = True
        # drain the 5m fast window; the page must clear while the slow
        # 6h/3d windows legitimately still remember the storm (warn)
        out = None
        for _ in range(70):
            clock.advance(5)
            out = engine.tick()["f"]
        assert out["alert"] in (ALERT_OK, ALERT_WARN)
        assert out["alert"] != ALERT_PAGE
        assert out["burn_rate"]["5m"] == 0.0
        assert out["burn_rate"]["3d"] > 0.0
        assert out["error_budget_remaining"] == pytest.approx(
            1.0 - out["burn_rate"]["3d"], abs=1e-6
        )
        # edge-triggered per INDEPENDENT tier: one storm, one page
        # breach — and the warn tier (whose slow windows also crossed
        # during the storm) counted its own single rising edge instead
        # of being shadowed by the concurrent page
        assert out["breaches"]["page"] == 1
        assert out["breaches"]["warn"] == 1

    def test_burn_rate_math(self):
        clock = _Clock()
        fresh = [True]
        engine = self._storm_engine(clock, fresh)
        engine.tick()
        fresh[0] = False
        for _ in range(10):  # 100% bad for 100 s
            clock.advance(10)
            engine.tick()
        out = engine.tick()["f"]
        # all-bad window: bad fraction 1.0, burn = 1 / (1 - 0.999)
        assert out["burn_rate"]["5m"] == pytest.approx(1000.0, rel=1e-6)

    def test_gauges_live_in_engine_counters(self):
        clock = _Clock()
        engine = SLOEngine(
            [SLO(name="f", sli="freshness", objective=0.9)],
            freshness=lambda: (True, ""),
            clock=clock,
        )
        engine.tick()
        text = engine.counters.prometheus_text()
        families = trace.parse_prometheus_text(text)
        assert "pas_slo_compliance" in families
        assert "pas_slo_burn_rate" in families
        # window label per series
        windows = {
            labels["window"]
            for _n, labels, _v in families["pas_slo_burn_rate"]["samples"]
        }
        assert windows == {"5m", "1h", "6h", "3d"}
        # and NOT in the process-wide COUNTERS (the off-path guarantee
        # rides on this separation)
        assert trace.COUNTERS.get(
            "pas_slo_compliance", kind="gauge", labels={"slo": "f"}
        ) == 0

    def test_readiness_condition_is_informational(self):
        clock = _Clock()
        fresh = [False]
        engine = SLOEngine(
            [SLO(name="f", sli="freshness", objective=0.999)],
            freshness=lambda: (fresh[0], ""),
            clock=clock,
        )
        engine.tick()
        for _ in range(5):
            clock.advance(10)
            engine.tick()
        ok, reason = engine.readiness_condition()
        assert ok is True  # burning never yanks the replica
        assert "f(" in reason

    def test_window_rings_stay_bounded(self):
        clock = _Clock()
        engine = SLOEngine(
            [SLO(name="f", sli="freshness", objective=0.9)],
            freshness=lambda: (True, ""),
            clock=clock,
            window_slots=64,
        )
        for _ in range(5000):
            clock.advance(1.0)
            engine.tick()
        for ring in engine._rings.values():
            assert len(ring._entries) <= 66, (
                f"{ring.window_s}s ring grew to {len(ring._entries)}"
            )

    def test_snapshot_is_readable_before_first_tick(self):
        engine = SLOEngine(
            [SLO(name="f", sli="freshness", objective=0.9)],
            freshness=lambda: (True, ""),
            clock=_Clock(),
        )
        snap = engine.snapshot()
        assert snap["enabled"] is True
        assert snap["slos"][0]["name"] == "f"


def _extender_with_engine(num_nodes=32):
    ext, names = build_extender(num_nodes, device=True)
    engine = SLOEngine(default_slos(), recorders=[ext.recorder])
    engine.tick()
    return ext, names, engine


class TestDebugSloEndpoint:
    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_codes_and_payload(self, serving):
        ext, _names, engine = _extender_with_engine()
        server = (
            start_async(ext) if serving == "async" else start_threaded(ext)
        )
        try:
            # 404 while unwired (--slo=off)
            status, _h, body = get_request(server.port, "/debug/slo")
            assert status == 404
            assert b"error" in body
            # 405 on non-GET
            ext.slo = engine
            status, _h, _b = raw_request(
                server.port, post_bytes("/debug/slo", b"{}")
            )
            assert status == 405
            # 200 with the compliance payload once wired
            status, _h, body = get_request(server.port, "/debug/slo")
            assert status == 200
            snap = json.loads(body)
            assert snap["enabled"] is True
            names = {row["name"] for row in snap["slos"]}
            assert {"verb_availability", "prioritize_p99"} <= names
            for row in snap["slos"]:
                assert "compliance" in row
                assert set(row["burn_rate"]) == {"5m", "1h", "6h", "3d"}
        finally:
            server.shutdown()

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_metrics_gains_slo_families_only_when_wired(self, serving):
        ext, _names, engine = _extender_with_engine()
        server = (
            start_async(ext) if serving == "async" else start_threaded(ext)
        )
        try:
            status, _h, body = get_request(server.port, "/metrics")
            assert status == 200
            assert b"pas_slo_" not in body, "--slo=off must emit nothing"
            ext.slo = engine
            status, _h, body = get_request(server.port, "/metrics")
            families = trace.parse_prometheus_text(body.decode())
            assert "pas_slo_compliance" in families
            assert "pas_slo_error_budget_remaining" in families
            assert "pas_slo_burn_rate" in families
        finally:
            server.shutdown()


class TestShedVisibility:
    def test_async_server_wires_its_counters_into_the_engine(self):
        """The admission-shed counter lives in the AsyncServer's
        layer-local CounterSet; an engine attached before the server is
        built (the mains' order) must see it — otherwise a saturated
        queue shedding half the traffic scores availability 1.0."""
        clock = _Clock()
        ext, _names = build_extender(8, device=True)
        engine = SLOEngine(
            default_slos(), recorders=[ext.recorder], clock=clock
        )
        ext.slo = engine
        server = start_async(ext)
        try:
            assert server.counters in engine.counter_sets
            engine.tick()
            for _ in range(8):
                ext.recorder.observe("prioritize", 0.001)
            server.counters.inc("pas_serving_rejected_total", 2)
            clock.advance(10)
            out = engine.tick()["verb_availability"]
            assert out["compliance"] == pytest.approx(0.8)
            # idempotent: a second server for the same scheduler must
            # not double-count the first one's set
            assert engine.counter_sets.count(server.counters) == 1
        finally:
            server.shutdown()


class TestOffPathPins:
    def test_off_is_byte_identical_on_the_wire(self):
        """ISSUE 10 acceptance: wiring (or not wiring) the engine never
        changes a verb response byte — the engine reads passively."""
        ext_off, names, _ = _extender_with_engine()
        ext_on, _names2, engine = _extender_with_engine()
        ext_on.slo = engine
        body = make_bodies(names, "nodenames", count=1)[0]
        for verb in ("prioritize", "filter"):
            request = HTTPRequest(
                method="POST",
                path=f"/scheduler/{verb}",
                headers={"Content-Type": "application/json"},
                body=body,
            )
            off = getattr(ext_off, verb)(request)
            on = getattr(ext_on, verb)(request)
            assert off.status == on.status
            assert off.body == on.body

    def test_flag_default_builds_nothing(self):
        from platform_aware_scheduling_tpu.cmd import common, gas, tas

        args = tas.build_arg_parser().parse_args([])
        assert args.slo == "off"
        ext, _names = build_extender(8, device=True)
        assert common.build_slo_engine(args, ext) is None
        assert ext.slo is None
        assert "pas_slo_" not in ext.metrics_text()
        # GAS offers the same flags (shared helper; no drift)
        gas_args = gas.build_arg_parser().parse_args([])
        assert gas_args.slo == "off"

    def test_flag_on_wires_tas_defaults(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        args = tas.build_arg_parser().parse_args(
            [
                "--slo", "on",
                "--sloConfig",
                '[{"name": "filter_p99", "disabled": true}]',
            ]
        )
        ext, _names = build_extender(8, device=True)
        engine = common.build_slo_engine(args, ext, cache=ext.cache)
        assert engine is not None
        assert ext.slo is engine
        names = set(engine.slos)
        assert "telemetry_freshness" in names  # TAS default set
        assert "eviction_safety" in names
        assert "filter_p99" not in names  # config disable applied
        assert engine.freshness is not None
        # readiness grows the informational condition
        conditions = dict(ext.readiness_conditions())
        assert "slo_burn" in conditions
        ok, _reason = conditions["slo_burn"]()
        assert ok is True

    def test_gas_engine_defaults(self):
        from platform_aware_scheduling_tpu.cmd import common, gas
        from platform_aware_scheduling_tpu.gas.scheduler import GASExtender
        from platform_aware_scheduling_tpu.testing.fake_kube import (
            FakeKubeClient,
        )

        args = gas.build_arg_parser().parse_args(["--slo", "on"])
        ext = GASExtender(FakeKubeClient(), use_device=False)
        engine = common.build_slo_engine(args, ext)
        assert engine is not None and ext.slo is engine
        assert "gas_filter_p99" in engine.slos
        assert "telemetry_freshness" not in engine.slos  # no cache
        engine.tick()
        assert "pas_slo_compliance" in ext.metrics_text()

    def test_slo_period_flag(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        args = tas.build_arg_parser().parse_args(["--sloPeriod", "2s"])
        assert common.slo_period(args, 5.0) == pytest.approx(2.0)
        args = tas.build_arg_parser().parse_args([])
        assert common.slo_period(args, 5.0) == pytest.approx(5.0)


class TestDebugIndexCompleteness:
    """Satellite: every registered debug route appears in the GET /debug
    index on both front-ends, answers GET with a JSON payload (never the
    bare catch-all 404), answers non-GET with 405, and the async
    queue-bypass set is derived from the same index — new endpoints
    cannot silently drop out of any of the three."""

    EXPECTED = {
        "/healthz", "/readyz", "/metrics", "/debug/traces",
        "/debug/decisions", "/debug/rebalance", "/debug/gangs",
        "/debug/forecast", "/debug/leader", "/debug/slo",
        "/debug/wire", "/debug/profile", "/debug/record",
        "/debug/whatif", "/debug/control", "/debug/admission",
        "/debug/explain", "/debug/solve", "/debug/shard",
    }

    def test_index_names_every_debug_route(self):
        assert {e["path"] for e in DEBUG_ENDPOINTS} == self.EXPECTED

    def test_bypass_set_derived_from_index(self):
        assert QUEUE_BYPASS_PATHS == (
            self.EXPECTED - EXECUTOR_DEBUG_PATHS
        ) | {"/debug", "/debug/"}

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_every_indexed_route_is_served(self, serving):
        ext, _names, _engine = _extender_with_engine(num_nodes=8)
        server = (
            start_async(ext) if serving == "async" else start_threaded(ext)
        )
        try:
            status, _h, body = get_request(server.port, "/debug")
            assert status == 200
            endpoints = json.loads(body)["endpoints"]
            assert {e["path"] for e in endpoints} == self.EXPECTED
            for entry in sorted(endpoints, key=lambda e: e["path"]):
                path = entry["path"]
                method = entry.get("method", "GET")
                if method == "POST":
                    # POST routes flip the semantics: GET must 405,
                    # POST must be served (never the bare catch-all)
                    status, _h, body = get_request(server.port, path)
                    assert status == 405, f"GET {path} -> {status}"
                    status, _h, body = raw_request(
                        server.port, post_bytes(path, b"{}")
                    )
                    assert body, f"{path}: empty body is the catch-all 404"
                    json.loads(body)
                    assert status in (200, 400, 404, 503), (
                        f"{path} -> {status}"
                    )
                    continue
                status, _h, body = get_request(server.port, path)
                assert body, f"{path}: empty body is the catch-all 404"
                if path != "/metrics":
                    json.loads(body)  # every debug payload is JSON
                assert status in (200, 400, 404, 503), (
                    f"{path} -> {status}"
                )
                status, _h, _b = raw_request(
                    server.port, post_bytes(path, b"{}")
                )
                assert status == 405, f"{path}: non-GET must 405"
        finally:
            server.shutdown()

    def test_unknown_debug_path_is_catch_all(self):
        """The distinguishability this gate relies on: an UNROUTED debug
        path gets the bare empty-body 404, a routed-but-unwired one gets
        a JSON error body."""
        ext, _names, _engine = _extender_with_engine(num_nodes=8)
        server = Server(ext, metrics_provider=ext.metrics_text)
        request = HTTPRequest(
            method="POST",
            path="/debug/nonexistent",
            headers={"Content-Type": "application/json"},
            body=b"{}",
        )
        response = server.route(request)
        assert response.status == 404
        assert response.body == b""

"""Hand-crafted-body tests pinning the enforced-field scope of Go
type-mismatch decode parity, exactly as stated in the extender/types.py
module docstring (ADVICE r5 #1): fields INSIDE the enforced set raise
DecodeError on a type mismatch (the verbs then produce the reference's
decode-failure empty-200 quirk); fields OUTSIDE it are lenient raw
pass-through even where Go's fully-typed structs would reject them.
"""

import json

import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.extender.types import (
    Args,
    BindingArgs,
    DecodeError,
)


def _body(obj) -> bytes:
    return json.dumps(obj).encode()


NODES = {"items": [{"metadata": {"name": "n1"}}]}


class TestEnforcedFields:
    """Type mismatches inside the enforced scope are decode failures."""

    @pytest.mark.parametrize(
        "body",
        [
            {"Pod": 5, "Nodes": NODES},  # Pod not an object
            {"Pod": {"metadata": []}, "Nodes": NODES},  # metadata not object
            {"Pod": {"metadata": {"name": 5}}, "Nodes": NODES},
            {"Pod": {"metadata": {"namespace": []}}, "Nodes": NODES},
            {"Pod": {"metadata": {"labels": "x"}}, "Nodes": NODES},
            {"Pod": {"metadata": {"labels": {"a": 1}}}, "Nodes": NODES},
            {"Pod": {}, "Nodes": 7},  # Nodes not an object
            {"Pod": {}, "Nodes": {"items": 7}},  # items not a list
            {"Pod": {}, "Nodes": {"items": ["x"]}},  # entry not an object
            {"Pod": {}, "Nodes": {"items": [{"metadata": 5}]}},
            {"Pod": {}, "Nodes": {"items": [{"metadata": {"name": 5}}]}},
            {"Pod": {}, "NodeNames": "n1"},  # NodeNames not a list
            {"Pod": {}, "NodeNames": [5]},  # entry not a string
        ],
    )
    def test_args_type_mismatch_fails(self, body):
        with pytest.raises(DecodeError):
            Args.from_json(_body(body))

    @pytest.mark.parametrize(
        "body",
        [
            {"PodName": 5},
            {"PodNamespace": []},
            {"PodUID": {}},
            {"Node": 1.5},
        ],
    )
    def test_binding_type_mismatch_fails(self, body):
        with pytest.raises(DecodeError):
            BindingArgs.from_json(_body(body))


class TestLenientFields:
    """Everything outside the enforced scope passes through untyped, even
    where Go's typed structs would reject it (the documented boundary)."""

    @pytest.mark.parametrize(
        "body",
        [
            # Pod.spec / Pod.status may hold any JSON type
            {"Pod": {"spec": 5, "metadata": {"name": "p"}}, "Nodes": NODES},
            {"Pod": {"status": []}, "Nodes": NODES},
            # node labels/annotations/status are raw pass-through; a
            # non-string node label is a Go UnmarshalTypeError but is
            # accepted here (observable on hand-crafted bodies only)
            {
                "Pod": {},
                "Nodes": {
                    "items": [
                        {
                            "metadata": {
                                "name": "n1",
                                "labels": {"a": 1},
                                "annotations": 7,
                            },
                            "status": "up",
                        }
                    ]
                },
            },
            # unknown top-level and nested keys of any type are dropped
            # or carried, never decode failures
            {"Pod": {"metadata": {"name": "p", "extra": {}}}, "Junk": [1]},
        ],
    )
    def test_args_lenient_accept(self, body):
        args = Args.from_json(_body(body))
        assert args.pod is not None

    def test_null_entries_keep_go_zero_values(self):
        args = Args.from_json(
            _body({"Pod": {}, "NodeNames": ["n1", None, "n2"]})
        )
        assert args.node_names == ["n1", "", "n2"]


class TestQuirkThroughVerb:
    """An enforced-scope mismatch produces the decode-failure empty-200
    quirk through the Prioritize verb (telemetryscheduler.go:41-48)."""

    def _extender(self):
        from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
        from platform_aware_scheduling_tpu.tas.telemetryscheduler import (
            MetricsExtender,
        )

        return MetricsExtender(AutoUpdatingCache())

    def _request(self, obj) -> HTTPRequest:
        return HTTPRequest(
            method="POST",
            path="/scheduler/prioritize",
            headers={"Content-Type": "application/json"},
            body=_body(obj),
        )

    def test_enforced_mismatch_is_empty_200(self):
        response = self._extender().prioritize(
            self._request({"Pod": {"metadata": {"name": 5}}, "Nodes": NODES})
        )
        assert response.status == 200
        assert response.body == b""

    def test_lenient_body_reaches_the_handler(self):
        # same shape but with the mismatch on a LENIENT field: decode
        # succeeds and the no-policy-label path answers 400 + "[]"
        response = self._extender().prioritize(
            self._request({"Pod": {"spec": 5}, "Nodes": NODES})
        )
        assert response.status == 400
        assert response.body == b"[]\n"

"""PrioritizeFastPath: byte parity with the per-request paths, subset
consistency against the per-request kernel, cache invalidation."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.extender.types import (
    HostPriority,
    encode_host_priority_list,
)
from platform_aware_scheduling_tpu.ops.scoring import prioritize_kernel
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.fastpath import PrioritizeFastPath
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def build(op="GreaterThan", values=None):
    values = values or {"n1": 100, "n2": 50, "n3": 10, "n4": 70}
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default",
        "pol",
        TASPolicy.from_obj(
            make_policy("pol", strategies={"scheduleonmetric": [rule("m", op, 0)]})
        ),
    )
    cache.write_metric(
        "m", {n: NodeMetric(value=Quantity(str(v))) for n, v in values.items()}
    )
    return cache, mirror


def prioritize_request(names, pod_name="p"):
    return HTTPRequest(
        method="POST",
        path="/scheduler/prioritize",
        headers={"Content-Type": "application/json"},
        body=json.dumps(
            {
                "Pod": {
                    "metadata": {
                        "name": pod_name,
                        "namespace": "default",
                        "labels": {"telemetry-policy": "pol"},
                    }
                },
                "Nodes": {"items": [{"metadata": {"name": n}} for n in names]},
            }
        ).encode(),
    )


class TestByteParity:
    @pytest.mark.parametrize("op", ["GreaterThan", "LessThan"])
    def test_device_bytes_equal_host_bytes(self, op):
        """With distinct metric values the fast path emits byte-identical
        output to the exact host path."""
        cache, mirror = build(op=op)
        device = MetricsExtender(cache, mirror=mirror)
        host = MetricsExtender(cache, mirror=None)
        for names in (
            ["n1", "n2", "n3", "n4"],
            ["n3", "n1"],
            ["n2"],
            ["n1", "ghost", "n4"],
            ["ghost"],
            [],
        ):
            req = prioritize_request(names)
            out_device = device.prioritize(req)
            out_host = host.prioritize(req)
            assert out_device.body == out_host.body, (op, names)
            assert out_device.status == out_host.status

    def test_escaped_names_roundtrip(self):
        """Node names needing JSON escaping encode exactly like json.dumps."""
        cache, mirror = build(values={'we"ird\\name': 5, "plain": 3})
        device = MetricsExtender(cache, mirror=mirror)
        out = device.prioritize(prioritize_request(['we"ird\\name', "plain"]))
        assert json.loads(out.body) == [
            {"Host": 'we"ird\\name', "Score": 10},
            {"Host": "plain", "Score": 9},
        ]

    def test_scores_go_negative_past_rank_10(self):
        values = {f"n{i:03d}": 1000 - i for i in range(15)}
        cache, mirror = build(values=values)
        device = MetricsExtender(cache, mirror=mirror)
        out = json.loads(
            device.prioritize(prioritize_request(sorted(values))).body
        )
        assert [e["Score"] for e in out] == [10 - i for i in range(15)]


class TestSubsetConsistency:
    def test_subset_of_global_order_matches_per_request_kernel(self):
        """Restricting the global ranking to a candidate set must equal
        running the kernel with that candidate mask (incl. ties, which
        break by node interning index)."""
        rng = np.random.default_rng(7)
        values = {f"n{i:04d}": int(rng.integers(0, 50)) for i in range(200)}
        cache, mirror = build(values=values)
        compiled, view = mirror.policy_with_view("default", "pol")
        fast = PrioritizeFastPath()
        for trial in range(5):
            names = list(
                rng.choice(sorted(values), size=60, replace=False)
            )
            body = fast.prioritize_bytes(compiled, view, names)
            got = [e["Host"] for e in json.loads(body)]
            mask_np = np.zeros(view.node_capacity, dtype=bool)
            for n in names:
                mask_np[view.node_index[n]] = True
            res = prioritize_kernel(
                view.values,
                view.present,
                jnp.int32(compiled.scheduleonmetric_row),
                jnp.int32(compiled.scheduleonmetric_op),
                jnp.asarray(mask_np),
            )
            perm = np.asarray(res.perm)[: int(res.valid_count)]
            expected = [view.node_names[i] for i in perm]
            assert got == expected


class TestPlanPromotion:
    def test_planned_node_promoted_to_rank_one(self):
        cache, mirror = build()
        compiled, view = mirror.policy_with_view("default", "pol")
        fast = PrioritizeFastPath()
        body = fast.prioritize_bytes(
            compiled, view, ["n1", "n2", "n3"], planned="n3"
        )
        assert json.loads(body) == [
            {"Host": "n3", "Score": 10},
            {"Host": "n1", "Score": 9},
            {"Host": "n2", "Score": 8},
        ]

    def test_planned_node_outside_candidates_ignored(self):
        cache, mirror = build()
        compiled, view = mirror.policy_with_view("default", "pol")
        fast = PrioritizeFastPath()
        body = fast.prioritize_bytes(
            compiled, view, ["n1", "n2"], planned="n4"
        )
        assert [e["Host"] for e in json.loads(body)] == ["n1", "n2"]


class TestCacheInvalidation:
    def test_metric_update_invalidates_ranking(self):
        cache, mirror = build()
        device = MetricsExtender(cache, mirror=mirror)
        req = prioritize_request(["n1", "n2", "n3"])
        assert json.loads(device.prioritize(req).body)[0]["Host"] == "n1"
        cache.write_metric(
            "m",
            {n: NodeMetric(value=Quantity(str(v)))
             for n, v in {"n1": 1, "n2": 50, "n3": 10}.items()},
        )
        assert json.loads(device.prioritize(req).body)[0]["Host"] == "n2"

    def test_rankings_cached_within_version(self):
        cache, mirror = build()
        fast = PrioritizeFastPath()
        compiled, view = mirror.policy_with_view("default", "pol")
        fast.prioritize_bytes(compiled, view, ["n1"])
        key = (
            view.row_version(compiled.scheduleonmetric_row),
            compiled.scheduleonmetric_row,
            compiled.scheduleonmetric_op,
        )
        ranked = fast._rank[key]
        fast.prioritize_bytes(compiled, view, ["n2", "n3"])
        assert fast._rank[key] is ranked  # same array object, no recompute


class TestPrecomputeWiring:
    """VERDICT r2 #3: the mirror's post-publish hook must warm the
    fastpath so requests never pay the device pass under metric churn."""

    def _counting(self, monkeypatch):
        import platform_aware_scheduling_tpu.tas.fastpath as fp_mod

        counts = {"prioritize": 0, "filter": 0}
        real_prioritize = fp_mod.prioritize_kernel
        real_filter = fp_mod.filter_explain_kernel

        def count_prioritize(*a, **k):
            counts["prioritize"] += 1
            return real_prioritize(*a, **k)

        def count_filter(*a, **k):
            counts["filter"] += 1
            return real_filter(*a, **k)

        monkeypatch.setattr(fp_mod, "prioritize_kernel", count_prioritize)
        monkeypatch.setattr(fp_mod, "filter_explain_kernel", count_filter)
        return counts

    def _write_metrics(self, cache, values):
        cache.write_metric(
            "m", {n: NodeMetric(value=Quantity(str(v))) for n, v in values.items()}
        )

    def test_requests_never_pay_device_pass_under_churn(self, monkeypatch):
        counts = self._counting(monkeypatch)
        cache = AutoUpdatingCache()
        mirror = TensorStateMirror()
        mirror.attach(cache)
        cache.write_policy(
            "default",
            "pol",
            TASPolicy.from_obj(
                make_policy(
                    "pol",
                    strategies={
                        "scheduleonmetric": [rule("m", "GreaterThan", 0)],
                        "dontschedule": [rule("m", "GreaterThan", 1000)],
                    },
                )
            ),
        )
        ext = MetricsExtender(cache, mirror=mirror)
        rng = np.random.default_rng(7)
        names = [f"node-{i:03d}" for i in range(50)]
        for round_idx in range(5):
            # churn: every metric value changes -> new state version,
            # warmed synchronously in this (the writer's) thread
            values = {n: int(rng.integers(0, 10_000)) for n in names}
            self._write_metrics(cache, values)
            warmed = dict(counts)
            for _ in range(4):
                resp = ext.prioritize(prioritize_request(names))
                assert resp.status == 200
                scored = json.loads(resp.body)
                assert len(scored) == len(names)
                freq = HTTPRequest(
                    method="POST",
                    path="/scheduler/filter",
                    headers={"Content-Type": "application/json"},
                    body=prioritize_request(names).body,
                )
                assert ext.filter(freq).status == 200
            assert counts == warmed, (
                f"round {round_idx}: a request paid a device pass "
                f"(warmed={warmed}, after={counts})"
            )
            # the churn rounds themselves must each have re-warmed
            assert counts["prioritize"] >= round_idx + 1

    def test_response_table_warmed_not_built_on_request(self, monkeypatch):
        cache, mirror = build()
        ext = MetricsExtender(cache, mirror=mirror)
        # after the write above, the current view's table must already
        # carry whichever encoder variant serves
        table = ext.fastpath._table
        assert table is not None
        from platform_aware_scheduling_tpu.native import get_wirec

        if get_wirec() is not None:
            assert table._native is not None
        else:
            assert table._fragments is not None

    def test_warm_failure_never_breaks_writer(self, monkeypatch):
        cache, mirror = build()
        ext = MetricsExtender(cache, mirror=mirror)

        def boom(*a, **k):
            raise RuntimeError("warm explosion")

        monkeypatch.setattr(ext.fastpath, "precompute", boom)
        # the metric write (and its hook chain) must survive
        self._write_metrics(cache, {"n1": 1, "n2": 2})
        resp = ext.prioritize(prioritize_request(["n1", "n2"]))
        assert resp.status == 200

    def test_new_policy_warms_at_current_version(self, monkeypatch):
        counts = self._counting(monkeypatch)
        cache, mirror = build()
        ext = MetricsExtender(cache, mirror=mirror)
        before = dict(counts)
        # a second policy on the same metric, opposite op: registering it
        # must warm the new (row, op) pair without any metric write
        cache.write_policy(
            "default",
            "pol2",
            TASPolicy.from_obj(
                make_policy(
                    "pol2",
                    strategies={"scheduleonmetric": [rule("m", "LessThan", 0)]},
                )
            ),
        )
        assert counts["prioritize"] == before["prioritize"] + 1
        resp = ext.prioritize(prioritize_request(["n1", "n2"], pod_name="q"))
        assert resp.status == 200
        assert counts["prioritize"] == before["prioritize"] + 1  # no request pass

    def test_value_churn_keeps_table_and_unrelated_rankings(self, monkeypatch):
        counts = self._counting(monkeypatch)
        cache = AutoUpdatingCache()
        mirror = TensorStateMirror()
        mirror.attach(cache)
        for pol, metric in (("pa", "ma"), ("pb", "mb")):
            cache.write_policy(
                "default",
                pol,
                TASPolicy.from_obj(
                    make_policy(
                        pol,
                        strategies={
                            "scheduleonmetric": [rule(metric, "GreaterThan", 0)]
                        },
                    )
                ),
            )
        names = [f"n{i}" for i in range(20)]
        for m in ("ma", "mb"):
            cache.write_metric(
                m, {n: NodeMetric(value=Quantity(str(i))) for i, n in enumerate(names)}
            )
        ext = MetricsExtender(cache, mirror=mirror)
        table_before = ext.fastpath._table
        assert table_before is not None
        passes_before = counts["prioritize"]
        # churn ONLY metric "ma": mb's ranking must stay cached (keyed by
        # row content version, not global version) and the encode table
        # must survive (keyed by interning version)
        cache.write_metric(
            "ma",
            {n: NodeMetric(value=Quantity(str(100 - i))) for i, n in enumerate(names)},
        )
        assert counts["prioritize"] == passes_before + 1  # only ma re-ranked
        assert ext.fastpath._table is table_before  # no table rebuild
        # a brand-new node invalidates the table but not via value churn
        cache.write_metric(
            "ma",
            {
                n: NodeMetric(value=Quantity(str(i)))
                for i, n in enumerate(names + ["brand-new"])
            },
        )
        assert ext.fastpath._table is not table_before

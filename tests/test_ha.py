"""End-to-end HA control-plane suite (docs/robustness.md "HA & leader
election"): the multi-replica harness (testing/ha.py) proving the
exactly-one-actuator invariant, fenced actuation, the rebalance idle
reasons, and crash-safe gang reservation recovery.

Everything runs on one shared fake clock and one shared FakeKubeClient;
nothing sleeps and nothing is random.
"""

import json

import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.kube.retry import CircuitBreakerRegistry
from platform_aware_scheduling_tpu.testing.builders import make_gang_pod
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.testing.faults import FakeClock
from platform_aware_scheduling_tpu.testing.ha import (
    HAHarness,
    LEASE_NAME,
    POLICY_NAME,
)
from platform_aware_scheduling_tpu.utils import trace


def _prioritize(stack, num_nodes):
    body = json.dumps(
        {
            "Pod": {
                "metadata": {
                    "name": "probe",
                    "namespace": "default",
                    "labels": {"telemetry-policy": POLICY_NAME},
                }
            },
            "NodeNames": [f"node-{i}" for i in range(num_nodes)],
        }
    ).encode()
    return stack.extender.prioritize(
        HTTPRequest(
            method="POST",
            path="/scheduler/prioritize",
            headers={"Content-Type": "application/json"},
            body=body,
        )
    )


# ---------------------------------------------------------------------------
# the exactly-one-actuator invariant (ACCEPTANCE)
# ---------------------------------------------------------------------------


class TestExactlyOneActuator:
    def test_leader_crash_failover_zero_duplicates(self):
        """ACCEPTANCE: leader crash mid-convergence -> a standby holds
        the lease within the lease duration, the fleet's total
        evictions equal the single-replica baseline, and the eviction
        log holds zero duplicates."""
        ticks = 24
        baseline = HAHarness(replicas=1, max_moves=1)
        baseline.run(ticks)
        assert len(baseline.evictions()) > 0

        h = HAHarness(replicas=3, max_moves=1)
        h.tick()  # leader elected, first eviction in flight
        assert h.leaders() == ["replica-0"]
        h.crash(0)
        failover = None
        for t in range(ticks - 1):
            h.tick()
            assert len(h.leaders()) <= 1  # never two leaders
            if failover is None and h.leaders():
                failover = t + 1
        # takeover is legal after lease_duration; +1 tick of slack for
        # the tick that observes the expiry
        bound = int(h.lease_duration_s / h.period_s) + 1
        assert failover is not None and failover <= bound
        assert h.leaders() == ["replica-1"]
        assert len(h.evictions()) == len(baseline.evictions())
        assert h.duplicate_evictions() == []
        assert h.hot_node_load() == baseline.hot_node_load()

    def test_lease_flapping_matches_baseline_actuation(self):
        """Lease-API outage mid-episode: nobody holds the lease (the
        old leader self-expires), actuation pauses, and after recovery
        the fleet still lands on exactly the baseline eviction count."""
        ticks = 30
        baseline = HAHarness(replicas=1, max_moves=1)
        baseline.run(ticks)

        h = HAHarness(replicas=3, max_moves=1)
        h.tick()
        for verb in ("get_lease", "update_lease", "create_lease"):
            h.plan.outage(verb, status=503)
        h.run(6)
        assert h.leaders() == []  # local expiry demoted the old leader
        for verb in ("get_lease", "update_lease", "create_lease"):
            h.plan.clear(verb)
        h.run(ticks - 7)
        assert len(h.leaders()) == 1
        assert len(h.evictions()) == len(baseline.evictions())
        assert h.duplicate_evictions() == []
        assert h.hot_node_load() == baseline.hot_node_load()

    def test_deposed_leader_in_flight_eviction_is_fenced(self):
        """ACCEPTANCE: a leader deposed mid-cycle (locally still
        convinced; the lease has moved) reaches the actuator and is
        refused by the per-eviction fencing check — the move lands as
        skipped reason=fenced, and the cluster sees no eviction."""
        h = HAHarness(replicas=2, max_moves=1, lease_duration_s=1000.0)
        h.tick()
        a, b = h.replicas[0], h.replicas[1]
        assert a.is_leader()
        evictions_before = len(h.evictions())
        # depose a on the SERVER only: force-expire its grant, let b
        # take over (token bumps); a's local deadline is 1000 s out
        with h.fake._lock:
            h.fake._leases[("default", LEASE_NAME)]["spec"][
                "renewTime"
            ] = -1e9
        assert b.elector.tick() is True
        assert a.elector.is_leader() is True  # locally unaware
        # a's in-flight cycle: refresh + enforce exactly as a tick would
        h.publish_loads()
        a.cache.update_all_metrics(a.ft_metrics)
        a.strategy.enforce(a.enforcer, a.cache)
        assert len(h.evictions()) == evictions_before  # nothing evicted
        last = a.rebalancer.status()["last_plan"]
        assert "fenced" in last["skipped"], last
        # the refused fencing check also demoted a
        assert a.elector.is_leader() is False

    def test_followers_keep_serving_verbs(self):
        h = HAHarness(replicas=3)
        h.run(2)
        followers = [s for s in h.live() if not s.is_leader()]
        assert len(followers) == 2
        for stack in followers:
            response = _prioritize(stack, h.num_nodes)
            assert response.status == 200
            assert json.loads(response.body)  # real ranked payload

    def test_follower_never_patches_labels(self):
        """The deschedule label pass is leader-only: every node patch in
        the shared fake must have been written while its author held
        the lease — with one stable leader, followers write nothing."""
        h = HAHarness(replicas=3, rebalance_mode="off")
        h.run(4)
        # patches happened (the leader's pass) ...
        assert len(h.fake.node_patches) > 0
        # ... and only one replica ever held the lease in this run
        assert h.leaders() == ["replica-0"]
        # crash every replica but a follower: with no leader, NO new
        # patches appear even as enforcement keeps running
        h.crash(0)
        patches_at_crash = len(h.fake.node_patches)
        h.tick()  # follower ticks before takeover is legal
        assert len(h.fake.node_patches) == patches_at_crash


# ---------------------------------------------------------------------------
# rebalance idle reasons (/debug/rebalance; satellite)
# ---------------------------------------------------------------------------


class TestRebalanceIdleReasons:
    def test_follower_reason(self):
        h = HAHarness(replicas=2)
        h.run(2)
        follower = next(s for s in h.live() if not s.is_leader())
        status = follower.rebalancer.status()
        assert status["actuation"] == {"idle": True, "reason": "follower"}
        assert status["role"] == "follower"
        assert status["last_plan"]["idle_reason"] == "follower"
        leader = next(s for s in h.live() if s.is_leader())
        assert leader.rebalancer.status()["actuation"] == {
            "idle": False,
            "reason": None,
        }

    def test_degraded_reason_wins_on_leader(self):
        h = HAHarness(replicas=1)
        h.run(2)
        leader = h.live()[0]
        h.plan.outage("get_node_metric", status=503)
        h.run(6)  # telemetry goes stale -> evictions suspended
        status = leader.rebalancer.status()
        assert status["actuation"] == {"idle": True, "reason": "degraded"}
        assert status["last_plan"]["idle_reason"] == "degraded"

    def test_off_reason(self):
        h = HAHarness(replicas=1, rebalance_mode="off")
        h.run(2)
        status = h.live()[0].rebalancer.status()
        assert status["actuation"] == {"idle": True, "reason": "off"}

    def test_served_on_debug_rebalance(self):
        from wirehelpers import get_request, start_threaded

        h = HAHarness(replicas=2)
        h.run(2)
        follower = next(s for s in h.live() if not s.is_leader())
        server = start_threaded(follower.extender)
        try:
            status, _h, payload = get_request(server.port, "/debug/rebalance")
            assert status == 200
            snap = json.loads(payload)
            assert snap["actuation"] == {"idle": True, "reason": "follower"}
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# crash-safe gang reservations (ACCEPTANCE)
# ---------------------------------------------------------------------------


def _reserve(stack, harness, pod_name, group, size, topo):
    pod = make_gang_pod(pod_name, group, size, topology=topo)
    harness.fake.add_pod(pod)
    failed, _codes = stack.gangs.filter_overlay(pod, list(harness.mesh_nodes))
    return [n for n in harness.mesh_nodes if n not in failed]


class TestGangJournalRecovery:
    def test_restart_mid_reservation_recovers_the_slice(self):
        """ACCEPTANCE: kill and re-assemble the extender mid-reservation
        — the re-formed gang admits on the JOURNALED slice, members
        Filter onto exactly those nodes, and a competing gang cannot
        take them."""
        h = HAHarness(replicas=2, gang=True, mesh=(4, 4))
        h.run(1)
        stack = h.live()[0]
        reserved = _reserve(stack, h, "g1-m0", "job1", 4, "2x2")
        assert len(reserved) == 4
        # one member binds for real (nodeName lands in the fake)
        h.fake.bind_pod("default", "g1-m0", "uid-0", reserved[0])
        stack.gangs.observe_bind("default", "g1-m0", reserved[0])
        # SIGKILL + re-assembly: fresh in-memory state, shared journal
        h.crash(stack.index)
        revived = h.restart(stack.index)
        snap = revived.gangs.snapshot()
        assert len(snap["gangs"]) == 1
        entry = snap["gangs"][0]
        assert entry["state"] == "reserved"
        assert entry["reserved_nodes"] == reserved
        assert entry["bound"] == 1  # the live on-slice bind survived
        # a member Filter passes ONLY the recovered slice
        member = make_gang_pod("g1-m1", "job1", 4, topology="2x2")
        failed, _ = revived.gangs.filter_overlay(
            member, list(h.mesh_nodes)
        )
        assert [n for n in h.mesh_nodes if n not in failed] == reserved
        # a competing gang is pushed OFF the recovered slice
        other = make_gang_pod("g2-m0", "job2", 4, topology="2x2")
        failed2, _ = revived.gangs.filter_overlay(other, list(h.mesh_nodes))
        other_slice = set(h.mesh_nodes) - set(failed2)
        assert not (other_slice & set(reserved))
        # remaining members Filter (onto the slice) then bind -> the
        # recovered gang fully admits
        admitted_before = trace.COUNTERS.get("pas_gang_admitted_total")
        for i, node in enumerate(reserved):
            name = f"g1-m{i}"
            if i:
                pod_i = make_gang_pod(name, "job1", 4, topology="2x2")
                h.fake.add_pod(pod_i)
                revived.gangs.filter_overlay(pod_i, list(h.mesh_nodes))
                h.fake.bind_pod("default", name, f"uid-{i}", node)
            revived.gangs.observe_bind("default", name, node)
        assert (
            trace.COUNTERS.get("pas_gang_admitted_total")
            == admitted_before + 1
        )

    def test_contradicted_journal_is_discarded(self):
        """ACCEPTANCE: a journal whose bound member now runs OUTSIDE the
        journaled slice is discarded at recovery — replaying it is how
        a gang would straddle two slices."""
        h = HAHarness(replicas=1, gang=True, mesh=(4, 4))
        h.run(1)
        stack = h.live()[0]
        reserved = _reserve(stack, h, "x-m0", "jobx", 4, "2x2")
        h.fake.bind_pod("default", "x-m0", "uid", reserved[0])
        stack.gangs.observe_bind("default", "x-m0", reserved[0])
        # the cluster moves on while we are dead: the pod lands on a
        # node OUTSIDE the journaled slice
        off_slice = next(n for n in h.mesh_nodes if n not in reserved)
        with h.fake._lock:
            h.fake._pods[("default", "x-m0")]["spec"]["nodeName"] = off_slice
        discarded_before = trace.COUNTERS.get(
            "pas_gang_journal_discarded_total"
        )
        h.crash(0)
        revived = h.restart(0)
        assert revived.gangs.snapshot()["gangs"] == []
        assert (
            trace.COUNTERS.get("pas_gang_journal_discarded_total")
            == discarded_before + 1
        )

    def test_unbound_member_drops_bind_but_keeps_reservation(self):
        """A journaled bind whose pod never actually bound (the bind
        raced the crash) drops the BIND only; the reservation survives
        with a fresh TTL."""
        h = HAHarness(replicas=1, gang=True, mesh=(4, 4))
        h.run(1)
        stack = h.live()[0]
        reserved = _reserve(stack, h, "y-m0", "joby", 4, "2x2")
        # observe_bind WITHOUT a real fake bind: journal says bound,
        # cluster says the pod has no nodeName
        stack.gangs.observe_bind("default", "y-m0", reserved[0])
        h.crash(0)
        revived = h.restart(0)
        snap = revived.gangs.snapshot()
        assert len(snap["gangs"]) == 1
        assert snap["gangs"][0]["bound"] == 0
        assert snap["gangs"][0]["reserved_nodes"] == reserved

    def test_recovered_reservation_still_expires(self):
        h = HAHarness(replicas=1, gang=True, mesh=(4, 4), gang_ttl_s=5.0)
        h.run(1)
        stack = h.live()[0]
        _reserve(stack, h, "z-m0", "jobz", 4, "2x2")
        h.crash(0)
        revived = h.restart(0)
        assert len(revived.gangs.snapshot()["gangs"]) == 1
        h.clock.advance(6.0)  # past the re-armed TTL, nobody refreshes
        revived.gangs.prune()
        snap = revived.gangs.snapshot()
        assert snap["gangs"][0]["state"] == "forming"
        assert snap["gangs"][0]["reserved_nodes"] == []

    def test_recover_without_pods_provider_discards(self):
        """No live view means no validation: a tracker with a journal
        but no pods_provider must DISCARD journaled entries, not replay
        them unreconciled (the documented recovery-matrix stance)."""
        from platform_aware_scheduling_tpu.gang import GangJournal, GangTracker

        fake = FakeKubeClient()
        journal = GangJournal(fake)
        journal.save(
            {
                "gangs": [
                    {
                        "gang": "default/stale",
                        "state": "reserved",
                        "size": 2,
                        "topology": None,
                        "reserved_nodes": ["n0", "n1"],
                        "anchor": None,
                        "bound": {},
                        "members": [],
                    }
                ]
            }
        )
        tracker = GangTracker(nodes_provider=fake.list_nodes)
        tracker.journal = journal
        discarded_before = trace.COUNTERS.get(
            "pas_gang_journal_discarded_total"
        )
        assert tracker.recover() == 0
        assert tracker.snapshot()["gangs"] == []
        assert (
            trace.COUNTERS.get("pas_gang_journal_discarded_total")
            == discarded_before + 1
        )

    def test_journal_write_behind_and_breaker_gating(self):
        """Reservation changes journal write-behind; with the kube
        circuit open the write is SKIPPED (counted) and the tracker
        keeps working in memory — then heals on the next durable
        mutation after the circuit closes."""
        h = HAHarness(replicas=1, gang=True, mesh=(4, 4))
        h.run(1)
        stack = h.live()[0]
        writes_before = trace.COUNTERS.get("pas_gang_journal_writes_total")
        _reserve(stack, h, "a-m0", "joba", 4, "2x2")
        assert (
            trace.COUNTERS.get("pas_gang_journal_writes_total")
            == writes_before + 1
        )
        # TTL refreshes are not durable: another member Filter (same
        # reservation) writes nothing
        member = make_gang_pod("a-m1", "joba", 4, topology="2x2")
        stack.gangs.filter_overlay(member, list(h.mesh_nodes))
        assert (
            trace.COUNTERS.get("pas_gang_journal_writes_total")
            == writes_before + 1
        )
        # open the kube circuit: the next durable mutation skips
        kube_breaker = stack.breakers.breaker("kube")
        for _ in range(kube_breaker.failure_threshold):
            kube_breaker.record_failure()
        skipped_before = trace.COUNTERS.get(
            "pas_gang_journal_skipped_total",
            labels={"reason": "circuit_open"},
        )
        reserved_b = _reserve(stack, h, "b-m0", "jobb", 4, "2x2")
        assert reserved_b  # in-memory reservation still works
        assert (
            trace.COUNTERS.get(
                "pas_gang_journal_skipped_total",
                labels={"reason": "circuit_open"},
            )
            == skipped_before + 1
        )
        # circuit closes -> the next durable mutation persists BOTH
        kube_breaker.record_success()
        stack.gangs.release("default/jobb")
        snap = stack.gangs.journal.load()
        assert snap is not None
        assert [g["gang"] for g in snap["gangs"]] == ["default/joba"]

    def test_gang_sweep_is_leader_only(self):
        calls = []
        clock = FakeClock()
        from platform_aware_scheduling_tpu.gang import GangSpec, GangTracker
        from platform_aware_scheduling_tpu.gang.group import (
            STATE_BOUND,
            _Gang,
        )

        class NotLeader:
            def is_leader(self):
                return False

        tracker = GangTracker(
            nodes_provider=lambda: [],
            pods_provider=lambda: calls.append(1) or [],
            mesh_max_age_s=0.0,
            clock=clock.now,
        )
        # a bound gang whose members are all gone: sweep bait
        gang = _Gang(GangSpec("default/dead", 1, None), 0.0)
        gang.state = STATE_BOUND
        gang.reserved_nodes = ["n0"]
        gang.bound = {"default/ghost": "n0"}
        tracker._gangs["default/dead"] = gang
        tracker.leadership = NotLeader()
        clock.advance(10.0)
        tracker.prune()  # inline sweep path
        assert calls == []  # follower never lists cluster pods
        tracker.leadership = None
        clock.advance(10.0)
        tracker.prune()
        assert calls == [1]  # ungated (single-replica) sweeps as before


# ---------------------------------------------------------------------------
# assembly wiring + off-path
# ---------------------------------------------------------------------------


class TestAssemblyWiring:
    def test_assemble_attaches_leadership_everywhere(self):
        from platform_aware_scheduling_tpu.cmd.tas import assemble
        from platform_aware_scheduling_tpu.gang import GangJournal, GangTracker
        from platform_aware_scheduling_tpu.kube.lease import LeaseElector
        from platform_aware_scheduling_tpu.tas.metrics import (
            DummyMetricsClient,
        )

        fake = FakeKubeClient()
        clock = FakeClock()
        elector = LeaseElector(fake, "r0", lease_name="l", clock=clock.now)
        tracker = GangTracker(
            nodes_provider=fake.list_nodes, pods_provider=fake.list_pods
        )
        journal = GangJournal(fake)
        pieces = assemble(
            fake,
            DummyMetricsClient({}),
            sync_period_s=3600.0,
            rebalance_mode="dry-run",
            gang_tracker=tracker,
            leadership=elector,
            gang_journal=journal,
        )
        _cache, _mirror, extender, _controller, enforcer, stop = pieces
        try:
            assert extender.leadership is elector
            assert enforcer.leadership is elector
            assert extender.rebalancer.leadership is elector
            assert extender.rebalancer.actuator.leadership is elector
            assert tracker.leadership is elector
            assert tracker.journal is journal
            names = [n for n, _ in extender.readiness_conditions()]
            assert "leadership" in names
        finally:
            stop.set()

    def test_assemble_recovers_journal_before_serving(self):
        from platform_aware_scheduling_tpu.cmd.tas import assemble
        from platform_aware_scheduling_tpu.gang import GangJournal, GangTracker
        from platform_aware_scheduling_tpu.tas.metrics import (
            DummyMetricsClient,
        )

        fake = FakeKubeClient()
        fake.add_mesh(2, 2)
        # a journal written by a previous life
        journal = GangJournal(fake)
        journal.save(
            {
                "gangs": [
                    {
                        "gang": "default/old",
                        "state": "reserved",
                        "size": 2,
                        "topology": [1, 2],
                        "reserved_nodes": ["mesh-0-0", "mesh-0-1"],
                        "anchor": [0, 0, 1, 2],
                        "bound": {},
                        "members": [],
                    }
                ]
            }
        )
        tracker = GangTracker(
            nodes_provider=fake.list_nodes, pods_provider=fake.list_pods
        )
        pieces = assemble(
            fake,
            DummyMetricsClient({}),
            sync_period_s=3600.0,
            gang_tracker=tracker,
            gang_journal=journal,
        )
        stop = pieces[-1]
        try:
            snap = tracker.snapshot()
            assert [g["gang"] for g in snap["gangs"]] == ["default/old"]
            assert snap["gangs"][0]["reserved_nodes"] == [
                "mesh-0-0",
                "mesh-0-1",
            ]
        finally:
            stop.set()

    def test_off_path_untouched(self):
        """Single-replica assembly without --leaderElect: no leadership
        anywhere, the actuator unfenced, the enforcer ungated — and the
        flags parse with HA off by default."""
        from platform_aware_scheduling_tpu.cmd import gas, tas
        from platform_aware_scheduling_tpu.cmd.tas import assemble
        from platform_aware_scheduling_tpu.tas.metrics import (
            DummyMetricsClient,
        )

        args = tas.build_arg_parser().parse_args([])
        assert args.leaderElect is False
        assert args.gangJournal == "off"
        from platform_aware_scheduling_tpu.cmd import common

        assert common.build_lease_elector(args, FakeKubeClient()) is None
        assert common.build_gang_journal(args, FakeKubeClient()) is None
        # GAS has no HA machinery: the flags must not exist there
        gas_args = gas.build_arg_parser().parse_args([])
        assert not hasattr(gas_args, "leaderElect")
        with pytest.raises(SystemExit):
            gas.build_arg_parser().parse_args(["--leaderElect"])

        pieces = assemble(
            FakeKubeClient(),
            DummyMetricsClient({}),
            sync_period_s=3600.0,
            rebalance_mode="dry-run",
        )
        _cache, _mirror, extender, _controller, enforcer, stop = pieces
        try:
            assert extender.leadership is None
            assert enforcer.leadership is None
            assert extender.rebalancer.leadership is None
            assert extender.rebalancer.actuator.leadership is None
            names = [n for n, _ in extender.readiness_conditions()]
            assert "leadership" not in names
            status = extender.rebalancer.status()
            assert status["role"] is None
            assert status["actuation"]["reason"] is None
        finally:
            stop.set()

    def test_ha_flags_parse_and_build(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        args = tas.build_arg_parser().parse_args(
            [
                "--leaderElect",
                "--leaseName", "my-lease",
                "--leaseDuration", "30s",
                "--leaseRenewPeriod", "7s",
                "--replicaId", "pod-3",
                "--gang", "on",
                "--gangJournal", "on",
                "--gangJournalName", "my-journal",
            ]
        )
        elector = common.build_lease_elector(args, FakeKubeClient())
        assert elector is not None
        assert elector.identity == "pod-3"
        assert elector.lease_name == "my-lease"
        assert elector.lease_duration_s == 30.0
        assert elector.renew_period_s == 7.0
        journal = common.build_gang_journal(
            args, FakeKubeClient(), CircuitBreakerRegistry()
        )
        assert journal is not None
        # the ledger is replica-local: under --leaderElect the journal
        # name carries the replica identity so N replicas can never
        # last-writer-wins clobber each other's reservations
        assert journal.name == "my-journal-pod-3"
        # without leader election (single replica) the bare name serves
        args_single = tas.build_arg_parser().parse_args(
            ["--gang", "on", "--gangJournal", "on",
             "--gangJournalName", "solo-journal"]
        )
        solo = common.build_gang_journal(args_single, FakeKubeClient())
        assert solo is not None and solo.name == "solo-journal"
        # journal without --gang=on is pointless: explicitly None
        args2 = tas.build_arg_parser().parse_args(["--gangJournal", "on"])
        assert common.build_gang_journal(args2, FakeKubeClient()) is None

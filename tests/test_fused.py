"""models/fused.py — the fused TAS+GAS solve must reproduce the
sequential host TAS-then-GAS composition decision-for-decision
(BASELINE config #4; reference tas+gas-extender-configmap.yaml chaining,
telemetryscheduler.go:128-149 + gpuscheduler/scheduler.go:200-257)."""

import numpy as np
import pytest

from benchmarks.configs import (
    _fused_problem,
    _host_fit_node,
    _host_fused_control,
)
from platform_aware_scheduling_tpu.models.fused import (
    _all_fits,
    fused_schedule,
)


def _solve(num_nodes, num_pods, seed=7, **kw):
    state, pods, req_class, gas, requests, max_gpus, hosts = _fused_problem(
        num_nodes=num_nodes, num_pods=num_pods, seed=seed, **kw
    )
    out = fused_schedule(state, pods, req_class, gas, requests, max_gpus)
    host_assign, _ = _host_fused_control(
        state, pods, req_class, hosts, num_nodes, num_pods
    )
    return out, host_assign, (state, pods, req_class, gas, requests,
                              max_gpus, hosts)


class TestFusedParity:
    def test_parity_small(self):
        out, host_assign, _ = _solve(num_nodes=32, num_pods=12)
        assert (np.asarray(out.node_for_pod) == host_assign).all()

    def test_parity_medium(self):
        out, host_assign, _ = _solve(
            num_nodes=200, num_pods=64, num_cards=4, num_classes=4, seed=11
        )
        assert (np.asarray(out.node_for_pod) == host_assign).all()

    def test_parity_scarce_cards(self):
        """Tight card capacity: many pods contend for few feasible nodes,
        so fits[T, N] columns must flip as bookings land."""
        out, host_assign, _ = _solve(
            num_nodes=24, num_pods=40, num_cards=2, num_res=2, seed=3
        )
        dev = np.asarray(out.node_for_pod)
        assert (dev == host_assign).all()
        # scarcity actually exercised: some pods must be unassigned
        assert (dev == -1).any()

    def test_initial_fits_matches_host_walk(self):
        _, _, (state, pods, req_class, gas, requests, max_gpus, hosts) = (
            _solve(num_nodes=40, num_pods=4)
        )
        fits = np.asarray(_all_fits(gas, requests, max_gpus))
        for t in range(fits.shape[0]):
            for n in range(fits.shape[1]):
                ok, _ = _host_fit_node(
                    hosts["used"][n],
                    hosts["cap"][n],
                    hosts["need"][t],
                    hosts["need_active"][t],
                    hosts["num_gpus"][t],
                )
                assert fits[t, n] == ok, (t, n)

    def test_bookings_respect_card_capacity(self):
        from platform_aware_scheduling_tpu.ops import i64 as i64mod

        out, _, (state, pods, req_class, gas, requests, max_gpus, hosts) = (
            _solve(num_nodes=24, num_pods=40, num_cards=2, num_res=2, seed=5)
        )
        used = i64mod.to_int64_np(out.used)
        assert (used <= hosts["cap"][:, None, :]).all()
        # booked usage only ever grows
        assert (used >= hosts["used"]).all()

    def test_inactive_resource_not_booked(self):
        """Regression: a resource ABSENT from the request (need_active
        False) must not consume card capacity even when its padded need
        value is nonzero — the reference books only the request map's own
        keys (resource_map.go addRM).  Before the fix the device kernel
        added the padded value, diverging from the host walk."""
        import jax.numpy as jnp

        from benchmarks.configs import _i64_np
        from platform_aware_scheduling_tpu.ops import i64 as i64mod
        from platform_aware_scheduling_tpu.ops.binpack import (
            BinpackNodeState,
            BinpackRequest,
            binpack_kernel,
        )

        # one node, one card, 2 resources; res 1 is inactive but has a
        # huge padded need that would blow capacity if booked
        cap = np.array([[100, 10]], dtype=np.int64)
        used = np.zeros((1, 1, 2), dtype=np.int64)
        need = np.array([[[50, 999]]], dtype=np.int64)  # [T=1, Tc=1, R=2]
        need_active = np.array([[[True, False]]])
        state = BinpackNodeState(
            used=_i64_np(used),
            capacity=_i64_np(cap),
            cap_present=jnp.ones((1, 2), dtype=bool),
            card_valid=jnp.ones((1, 1), dtype=bool),
            card_real=jnp.ones((1, 1), dtype=bool),
            card_order=jnp.zeros((1, 1), dtype=jnp.int32),
        )
        request = BinpackRequest(
            need=_i64_np(need[0]),
            need_active=jnp.asarray(need_active[0]),
            num_gpus=jnp.asarray(np.array([2], dtype=np.int32)),
            container_active=jnp.asarray(np.array([True])),
        )
        result = binpack_kernel(state, request, 2)
        # two shares of res0=50 fit in cap 100; the inactive res1 need of
        # 999 must not be booked or the second share would not fit
        assert bool(np.asarray(result.fits)[0])
        assert np.asarray(result.cards)[0].tolist() == [[0, 0]]

    @pytest.mark.parametrize("seed", range(6))
    def test_parity_seed_sweep(self, seed):
        """Host-control parity across random problem draws (shapes small,
        semantics full: random need_active, classes, capacities)."""
        out, host_assign, _ = _solve(
            num_nodes=48,
            num_pods=20,
            num_cards=3,
            num_res=2,
            num_classes=2,
            seed=seed,
        )
        assert (np.asarray(out.node_for_pod) == host_assign).all()

    def test_gspmd_node_sharded_matches_unsharded(self):
        """The fused solve under GSPMD node sharding (the multi-chip
        config-4 path, also asserted in dryrun_multichip) must equal the
        unsharded program exactly."""
        import jax

        from platform_aware_scheduling_tpu.models.fused import (
            shard_fused_inputs,
        )
        from platform_aware_scheduling_tpu.parallel.mesh import make_mesh

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
        state, pods, req_class, gas, requests, max_gpus, _ = _fused_problem(
            num_nodes=128, num_pods=16, seed=4
        )
        want = np.asarray(
            fused_schedule(
                state, pods, req_class, gas, requests, max_gpus
            ).node_for_pod
        )
        mesh = make_mesh(n_node_shards=8, n_pod_shards=1)
        sharded = shard_fused_inputs(
            mesh, state, pods, req_class, gas, requests
        )
        got = np.asarray(
            fused_schedule(*sharded, max_gpus).node_for_pod
        )
        assert (got == want).all()

    def test_capacity_left_consistent(self):
        out, host_assign, (state, *_rest) = _solve(num_nodes=32, num_pods=12)
        cap0 = np.asarray(state.capacity)
        cap_left = np.asarray(out.capacity_left)
        assigned = np.asarray(out.node_for_pod)
        booked = np.bincount(
            assigned[assigned >= 0], minlength=cap0.shape[0]
        )
        assert (cap_left == cap0 - booked).all()
        assert (cap_left >= 0).all()

"""ResourceMap arithmetic parity with the reference's overflow/clamp rules
(reference gpu-aware-scheduling/pkg/gpuscheduler/resource_map_test.go
behaviors)."""

import pytest

from platform_aware_scheduling_tpu.gas.resource_map import (
    INT64_MAX,
    InputError,
    OverflowError64,
    ResourceMap,
)


class TestAdd:
    def test_add_new_and_existing(self):
        rm = ResourceMap()
        rm.add("r", 5)
        rm.add("r", 7)
        assert rm["r"] == 12

    def test_add_negative_rejected(self):
        rm = ResourceMap(r=1)
        with pytest.raises(InputError):
            rm.add("r", -1)
        assert rm["r"] == 1

    def test_add_overflow_detected(self):
        rm = ResourceMap(r=INT64_MAX)
        with pytest.raises(OverflowError64):
            rm.add("r", 1)
        assert rm["r"] == INT64_MAX

    def test_add_to_missing_key_no_overflow_check(self):
        # fresh key skips the overflow branch, like the reference
        rm = ResourceMap()
        rm.add("r", INT64_MAX)
        assert rm["r"] == INT64_MAX


class TestSubtract:
    def test_subtract_basic(self):
        rm = ResourceMap(r=10)
        rm.subtract("r", 4)
        assert rm["r"] == 6

    def test_subtract_clamps_to_zero(self):
        rm = ResourceMap(r=3)
        rm.subtract("r", 10)
        assert rm["r"] == 0

    def test_subtract_missing_key_errors(self):
        rm = ResourceMap()
        with pytest.raises(InputError):
            rm.subtract("ghost", 1)

    def test_subtract_negative_rejected(self):
        rm = ResourceMap(r=1)
        with pytest.raises(InputError):
            rm.subtract("r", -1)


class TestTransactional:
    def test_add_rm_all_or_nothing(self):
        rm = ResourceMap(a=1, b=INT64_MAX)
        with pytest.raises(OverflowError64):
            rm.add_rm(ResourceMap(a=1, b=1))
        assert rm == {"a": 1, "b": INT64_MAX}  # untouched

    def test_subtract_rm_all_or_nothing(self):
        rm = ResourceMap(a=5)
        with pytest.raises(InputError):
            rm.subtract_rm(ResourceMap(a=1, ghost=1))
        assert rm == {"a": 5}

    def test_add_rm_success(self):
        rm = ResourceMap(a=1)
        rm.add_rm(ResourceMap(a=2, b=3))
        assert rm == {"a": 3, "b": 3}


class TestDivide:
    def test_divide(self):
        rm = ResourceMap(a=10, b=7)
        rm.divide(2)
        assert rm == {"a": 5, "b": 3}

    def test_divide_by_one_noop(self):
        rm = ResourceMap(a=9)
        rm.divide(1)
        assert rm == {"a": 9}

    def test_divide_bad_divider(self):
        with pytest.raises(InputError):
            ResourceMap(a=1).divide(0)

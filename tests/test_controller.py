"""TASPolicy controller tests: live informer over the fake kube client —
the active informer test the reference left commented out
(reference pkg/controller/controller_test.go:34-38)."""

import time

import pytest

from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache, CacheMissError
from platform_aware_scheduling_tpu.tas.controller import (
    InvalidStrategyError,
    TelemetryPolicyController,
    cast_strategy,
)
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import (
    TASPolicy,
    TASPolicyStrategy,
)
from platform_aware_scheduling_tpu.tas.strategies import core, deschedule, dontschedule
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient


def wait_until(pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def build():
    kube = FakeKubeClient()
    cache = AutoUpdatingCache()
    enforcer = core.MetricEnforcer(kube)
    enforcer.register_strategy_type(deschedule.Strategy())
    enforcer.register_strategy_type(dontschedule.Strategy())
    controller = TelemetryPolicyController(kube, cache, enforcer)
    return kube, cache, enforcer, controller


POLICY = make_policy(
    "demo-policy",
    strategies={
        "dontschedule": [rule("memory", "GreaterThan", 80)],
        "deschedule": [rule("memory", "GreaterThan", 90)],
        "scheduleonmetric": [rule("cpu", "LessThan", 0)],
    },
)


class TestCastStrategy:
    def test_known_types(self):
        strat = TASPolicyStrategy.from_obj(
            {"policyName": "p", "rules": [rule("m", "LessThan", 1)]}
        )
        for name in ("dontschedule", "deschedule", "scheduleonmetric"):
            instance = cast_strategy(name, strat)
            assert instance.strategy_type() == name
            assert instance.rules[0].metricname == "m"

    def test_unknown_type_raises(self):
        with pytest.raises(InvalidStrategyError):
            cast_strategy("labeling-v2", TASPolicyStrategy())


class TestControllerLive:
    def test_add_policy_via_watch(self):
        kube, cache, enforcer, controller = build()
        informer = controller.run()
        assert informer.wait_for_cache_sync()
        kube.create_taspolicy(POLICY)
        assert wait_until(
            lambda: _has_policy(cache, "default", "demo-policy")
        )
        # metrics registered (refcounted) for every rule
        assert set(cache.registered_metric_names()) == {"memory", "cpu"}
        # enforceable strategies registered under their types
        assert wait_until(
            lambda: len(enforcer.registered_strategies["deschedule"]) == 1
        )

    def test_update_policy_reregisters(self):
        kube, cache, enforcer, controller = build()
        controller.run().wait_for_cache_sync()
        kube.create_taspolicy(POLICY)
        assert wait_until(lambda: _has_policy(cache, "default", "demo-policy"))
        updated = make_policy(
            "demo-policy",
            strategies={
                "dontschedule": [rule("disk", "GreaterThan", 70)],
                "deschedule": [rule("memory", "GreaterThan", 95)],
                "scheduleonmetric": [rule("cpu", "LessThan", 0)],
            },
        )
        updated["metadata"]["resourceVersion"] = "2"
        kube.update_taspolicy(updated)
        assert wait_until(
            lambda: "disk" in cache.registered_metric_names()
        )
        pol = cache.read_policy("default", "demo-policy")
        assert pol.strategies["dontschedule"].rules[0].metricname == "disk"
        assert wait_until(
            lambda: any(
                s.rules[0].target == 95
                for s in enforcer.registered_strategies["deschedule"].values()
            )
        )

    def test_delete_policy_cleans_up(self):
        kube, cache, enforcer, controller = build()
        controller.run().wait_for_cache_sync()
        kube.create_taspolicy(POLICY)
        assert wait_until(lambda: _has_policy(cache, "default", "demo-policy"))
        kube.delete_taspolicy("default", "demo-policy")
        assert wait_until(
            lambda: not _has_policy(cache, "default", "demo-policy")
        )
        assert wait_until(
            lambda: len(enforcer.registered_strategies["deschedule"]) == 0
        )
        assert cache.registered_metric_names() == []

    def test_policies_present_before_start_are_replayed(self):
        kube, cache, _, controller = build()
        kube.create_taspolicy(POLICY)  # exists before the informer starts
        controller.run().wait_for_cache_sync()
        assert wait_until(lambda: _has_policy(cache, "default", "demo-policy"))

    def test_mirror_follows_controller(self):
        kube, cache, _, controller = build()
        mirror = TensorStateMirror()
        mirror.attach(cache)
        controller.run().wait_for_cache_sync()
        kube.create_taspolicy(POLICY)
        assert wait_until(
            lambda: mirror.policy("default", "demo-policy") is not None
        )
        compiled = mirror.policy("default", "demo-policy")
        assert compiled.dontschedule is not None
        assert compiled.scheduleonmetric_metric == "cpu"


def _has_policy(cache, ns, name) -> bool:
    try:
        cache.read_policy(ns, name)
        return True
    except CacheMissError:
        return False


class TestAssemble:
    def test_cmd_assemble_wires_everything(self):
        from platform_aware_scheduling_tpu.cmd.tas import assemble
        from platform_aware_scheduling_tpu.tas.metrics import DummyMetricsClient

        kube = FakeKubeClient()
        kube.set_node_metric("memory", "node1", "50")
        cache, mirror, extender, controller, enforcer, stop = assemble(
            kube, DummyMetricsClient({}), sync_period_s=0.05
        )
        try:
            kube.create_taspolicy(POLICY)
            assert wait_until(lambda: _has_policy(cache, "default", "demo-policy"))
            assert mirror is not None
            assert extender.mirror is mirror
            assert enforcer.is_registered("deschedule")
        finally:
            stop.set()

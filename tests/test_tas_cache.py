"""TAS cache + metrics client tests (reference pkg/cache/autoupdating_test.go,
pkg/metrics/client_test.go)."""

import time

import pytest

from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache, CacheMissError
from platform_aware_scheduling_tpu.tas.metrics import (
    CustomMetricsClient,
    DummyMetricsClient,
    MetricsError,
    NodeMetric,
    instance_of_mock_metric_client_map,
    wrap_metrics,
)
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def seeded_cache():
    cache = AutoUpdatingCache()
    cache.write_metric("dummyMetric1", None)  # register
    cache.write_metric(
        "dummyMetric1",
        {"node A": NodeMetric(value=Quantity("100")),
         "node B": NodeMetric(value=Quantity("200"))},
    )
    return cache


class TestAutoUpdatingCache:
    def test_read_write_metric(self):
        cache = seeded_cache()
        info = cache.read_metric("dummyMetric1")
        assert info["node A"].value.cmp_int64(100) == 0

    def test_read_missing_metric_raises(self):
        with pytest.raises(CacheMissError):
            AutoUpdatingCache().read_metric("nope")

    def test_register_does_not_clobber(self):
        cache = seeded_cache()
        # a second nil registration must preserve the data
        cache.write_metric("dummyMetric1", None)
        assert cache.read_metric("dummyMetric1")["node B"].value.cmp_int64(200) == 0

    def test_refcounted_delete(self):
        cache = seeded_cache()
        cache.write_metric("dummyMetric1", None)  # second registration (refcount 2)
        cache.delete_metric("dummyMetric1")
        # still present: one registration remains
        assert cache.read_metric("dummyMetric1")
        cache.delete_metric("dummyMetric1")
        with pytest.raises(CacheMissError):
            cache.read_metric("dummyMetric1")

    def test_policy_roundtrip(self):
        cache = AutoUpdatingCache()
        policy = TASPolicy(metadata={"name": "p", "namespace": "default"})
        cache.write_policy("default", "p", policy)
        assert cache.read_policy("default", "p").name == "p"
        cache.delete_policy("default", "p")
        with pytest.raises(CacheMissError):
            cache.read_policy("default", "p")

    def test_periodic_update_refreshes(self):
        """Values change after a ticker period (autoupdating_test.go:15-62)."""
        cache = AutoUpdatingCache()
        cache.write_metric("m", None)
        client = DummyMetricsClient({"m": {"n1": NodeMetric(value=Quantity("1"))}})
        stop = cache.start_periodic_update(0.02, client)
        try:
            deadline = time.time() + 2
            while time.time() < deadline:
                try:
                    if cache.read_metric("m")["n1"].value.cmp_int64(1) == 0:
                        break
                except CacheMissError:
                    pass
                time.sleep(0.01)
            assert cache.read_metric("m")["n1"].value.cmp_int64(1) == 0
            # now the backend changes; cache must follow
            client.store["m"] = {"n1": NodeMetric(value=Quantity("5"))}
            deadline = time.time() + 2
            while time.time() < deadline:
                if cache.read_metric("m")["n1"].value.cmp_int64(5) == 0:
                    break
                time.sleep(0.01)
            assert cache.read_metric("m")["n1"].value.cmp_int64(5) == 0
        finally:
            stop.set()

    def test_mirror_hooks_fire(self):
        cache = AutoUpdatingCache()
        events = []
        cache.on_metric_write.append(lambda name, data: events.append(("w", name)))
        cache.on_metric_delete.append(lambda name: events.append(("d", name)))
        cache.write_metric("m", None)
        cache.write_metric("m", {"n": NodeMetric(value=Quantity("1"))})
        cache.delete_metric("m")
        assert events == [("w", "m"), ("w", "m"), ("d", "m")]


class TestLastKnownGoodRetention:
    """ISSUE 5 satellite: a failed per-metric refresh preserves the
    prior NodeMetricsInfo (the store's write-nil rule) while the metric
    keeps AGING for freshness, and the refresh-error counter carries a
    bounded ``reason`` label."""

    def _cache_on_fake_clock(self):
        from platform_aware_scheduling_tpu.testing.faults import FakeClock
        from platform_aware_scheduling_tpu.utils.tracing import CounterSet

        clock = FakeClock()
        counters = CounterSet()
        cache = AutoUpdatingCache(counters=counters, clock=clock.now)
        cache._refresh_period = 1.0
        cache.write_metric(
            "m1", {"node A": NodeMetric(value=Quantity("7"))}
        )
        cache.write_metric("m1")  # register for refresh
        return cache, clock, counters

    def test_failed_refresh_keeps_values_but_ages_them(self):
        cache, clock, counters = self._cache_on_fake_clock()
        good = DummyMetricsClient(
            {"m1": {"node A": NodeMetric(value=Quantity("7"))}}
        )
        cache.update_all_metrics(good)
        assert cache.metric_ages()["m1"] == 0
        fresh_ok, _ = cache.telemetry_freshness()
        assert fresh_ok
        # the API goes away; passes keep running
        bad = DummyMetricsClient({})
        for _ in range(4):
            clock.advance(1.0)
            cache.update_all_metrics(bad)
        # last-known-good value still served (write-nil rule)...
        assert cache.read_metric("m1")["node A"].value.cmp_int64(7) == 0
        # ...but the metric AGED: freshness decayed past the 3x bound
        assert cache.metric_ages()["m1"] == pytest.approx(4.0)
        fresh_ok, reason = cache.telemetry_freshness()
        assert not fresh_ok and "m1" in reason

    def test_refresh_errors_carry_reason_label(self):
        from platform_aware_scheduling_tpu.kube.retry import CircuitOpenError
        from platform_aware_scheduling_tpu.tas.cache import (
            _refresh_error_reason,
        )

        cache, clock, counters = self._cache_on_fake_clock()

        class Failing:
            def __init__(self, exc):
                self.exc = exc

            def get_node_metric(self, name):
                raise self.exc

        cache.update_all_metrics(Failing(MetricsError("no metric m1 found")))
        assert counters.get(
            "pas_telemetry_refresh_errors_total",
            labels={"reason": "no_data"},
        ) == 1
        cache.update_all_metrics(Failing(CircuitOpenError("metrics")))
        assert counters.get(
            "pas_telemetry_refresh_errors_total",
            labels={"reason": "circuit_open"},
        ) == 1
        # unlabeled get() still sums across reasons (dashboards keep
        # their totals)
        assert counters.get("pas_telemetry_refresh_errors_total") == 2
        # classifier edges stay bounded
        from platform_aware_scheduling_tpu.kube.client import KubeError

        assert _refresh_error_reason(KubeError("x", status=429)) == "throttled"
        assert _refresh_error_reason(KubeError("x", status=503)) == "server_error"
        assert _refresh_error_reason(TimeoutError()) == "network"
        assert _refresh_error_reason(ValueError("weird")) == "fetch_error"
        # the PRODUCTION path: CustomMetricsClient wraps everything in a
        # bare MetricsError whose __cause__ carries the real error — the
        # classifier must walk the chain, not collapse to fetch_error
        def wrapped(cause):
            try:
                try:
                    raise cause
                except Exception as inner:
                    raise MetricsError("unable to fetch metrics") from inner
            except MetricsError as outer:
                return outer

        assert _refresh_error_reason(
            wrapped(KubeError("x", status=503))
        ) == "server_error"
        assert _refresh_error_reason(
            wrapped(CircuitOpenError("metrics"))
        ) == "circuit_open"


class TestHistoryRing:
    """ISSUE 8 satellite: the refresh-history ring's semantics under
    failure (docs/forecast.md).  A failed refresh appends NO sample while
    the last-known-good value keeps aging; the ring stays bounded at W
    across 10x W passes; a full delete drops the ring and the forecast
    gauges with the metric."""

    def _cache_on_fake_clock(self, window=4):
        from platform_aware_scheduling_tpu.testing.faults import FakeClock
        from platform_aware_scheduling_tpu.utils.tracing import CounterSet

        clock = FakeClock()
        counters = CounterSet()
        cache = AutoUpdatingCache(counters=counters, clock=clock.now)
        cache._refresh_period = 1.0
        cache.configure_history(window)
        cache.write_metric("m1")  # register for refresh
        return cache, clock, counters

    def test_failed_refresh_appends_nothing_while_lkg_ages(self):
        cache, clock, _counters = self._cache_on_fake_clock()
        good = DummyMetricsClient(
            {"m1": {"node A": NodeMetric(value=Quantity("7"))}}
        )
        cache.update_all_metrics(good)
        clock.advance(1.0)
        cache.update_all_metrics(good)
        t_last_good = clock.now()
        gen_before = cache.history_generation()
        _gen, rings = cache.history_snapshot()
        assert len(rings["m1"]) == 2
        # the API goes away; passes keep running but the ring is frozen
        bad = DummyMetricsClient({})
        for _ in range(3):
            clock.advance(1.0)
            cache.update_all_metrics(bad)
        assert cache.history_generation() == gen_before
        _gen, rings = cache.history_snapshot()
        assert len(rings["m1"]) == 2  # no fabricated samples
        # the GAP is visible: the newest stamp predates the failures
        assert rings["m1"][-1][0] == pytest.approx(t_last_good)
        # while the LKG value is still served AND aging
        assert cache.read_metric("m1")["node A"].value.cmp_int64(7) == 0
        assert cache.metric_ages()["m1"] == pytest.approx(3.0)

    def test_ring_bounded_at_window_across_many_passes(self):
        window = 4
        cache, clock, _counters = self._cache_on_fake_clock(window)
        for i in range(10 * window):
            clock.advance(1.0)
            cache.update_all_metrics(
                DummyMetricsClient(
                    {"m1": {"n": NodeMetric(value=Quantity(str(i)))}}
                )
            )
        _gen, rings = cache.history_snapshot()
        assert len(rings["m1"]) == window
        # the ring holds exactly the LAST W samples, oldest first
        values = [sample["n"] for _stamp, sample in rings["m1"]]
        assert values == [
            (10 * window - window + i) * 1000 for i in range(window)
        ]

    def test_delete_metric_drops_ring_and_gauges(self):
        from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
        from platform_aware_scheduling_tpu.forecast import Forecaster

        cache, clock, counters = self._cache_on_fake_clock()
        mirror = TensorStateMirror()
        mirror.attach(cache)
        forecaster = Forecaster(
            cache, mirror, window=4, period_s=1.0, counters=counters,
            clock=clock.now,
        )
        for i in range(3):
            clock.advance(1.0)
            cache.update_all_metrics(
                DummyMetricsClient(
                    {"m1": {"n": NodeMetric(value=Quantity(str(i)))}}
                )
            )
        assert forecaster.ensure_current() is not None
        # the ramp (0, 1, 2) publishes a positive slope gauge
        assert counters.get(
            "pas_forecast_metric_slope", labels={"metric": "m1"},
            kind="gauge",
        ) > 0
        gen_before = cache.history_generation()
        cache.delete_metric("m1")
        # the ring is gone (a re-registration must not forecast from a
        # ghost series) and the generation moved so consumers refit
        _gen, rings = cache.history_snapshot()
        assert "m1" not in rings
        assert cache.history_generation() > gen_before
        # ...and the per-metric gauges died with it (a removed series
        # reads back as the 0 default)
        assert counters.get(
            "pas_forecast_metric_slope", labels={"metric": "m1"},
            kind="gauge",
        ) == 0
        assert counters.get(
            "pas_telemetry_metric_age_seconds", labels={"metric": "m1"},
            kind="gauge",
        ) == 0


class TestMetricsClient:
    def test_wrap_metrics_default_window(self):
        info = wrap_metrics(
            {"items": [{"describedObject": {"kind": "Node", "name": "n1"},
                        "value": "50"}]}
        )
        assert info["n1"].window_seconds == 60.0
        assert info["n1"].value.cmp_int64(50) == 0

    def test_wrap_metrics_explicit_window(self):
        info = wrap_metrics(
            {"items": [{"describedObject": {"name": "n1"}, "windowSeconds": 30,
                        "value": "104857600000m"}]}
        )
        assert info["n1"].window_seconds == 30.0
        assert info["n1"].value.cmp_int64(104857600) == 0

    def test_custom_metrics_client_via_fake(self):
        fake = FakeKubeClient()
        fake.set_node_metric("health_metric", "node1", "0")
        fake.set_node_metric("health_metric", "node2", "1")
        client = CustomMetricsClient(fake)
        info = client.get_node_metric("health_metric")
        assert set(info) == {"node1", "node2"}

    def test_empty_items_error(self):
        client = CustomMetricsClient(FakeKubeClient())
        with pytest.raises(MetricsError, match="no metrics returned"):
            client.get_node_metric("missing")

    def test_dummy_client(self):
        client = DummyMetricsClient(instance_of_mock_metric_client_map())
        assert client.get_node_metric("dummyMetric1")["node A"].value.cmp_int64(100) == 0
        with pytest.raises(MetricsError):
            client.get_node_metric("other")

"""TAS cache + metrics client tests (reference pkg/cache/autoupdating_test.go,
pkg/metrics/client_test.go)."""

import time

import pytest

from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache, CacheMissError
from platform_aware_scheduling_tpu.tas.metrics import (
    CustomMetricsClient,
    DummyMetricsClient,
    MetricsError,
    NodeMetric,
    instance_of_mock_metric_client_map,
    wrap_metrics,
)
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def seeded_cache():
    cache = AutoUpdatingCache()
    cache.write_metric("dummyMetric1", None)  # register
    cache.write_metric(
        "dummyMetric1",
        {"node A": NodeMetric(value=Quantity("100")),
         "node B": NodeMetric(value=Quantity("200"))},
    )
    return cache


class TestAutoUpdatingCache:
    def test_read_write_metric(self):
        cache = seeded_cache()
        info = cache.read_metric("dummyMetric1")
        assert info["node A"].value.cmp_int64(100) == 0

    def test_read_missing_metric_raises(self):
        with pytest.raises(CacheMissError):
            AutoUpdatingCache().read_metric("nope")

    def test_register_does_not_clobber(self):
        cache = seeded_cache()
        # a second nil registration must preserve the data
        cache.write_metric("dummyMetric1", None)
        assert cache.read_metric("dummyMetric1")["node B"].value.cmp_int64(200) == 0

    def test_refcounted_delete(self):
        cache = seeded_cache()
        cache.write_metric("dummyMetric1", None)  # second registration (refcount 2)
        cache.delete_metric("dummyMetric1")
        # still present: one registration remains
        assert cache.read_metric("dummyMetric1")
        cache.delete_metric("dummyMetric1")
        with pytest.raises(CacheMissError):
            cache.read_metric("dummyMetric1")

    def test_policy_roundtrip(self):
        cache = AutoUpdatingCache()
        policy = TASPolicy(metadata={"name": "p", "namespace": "default"})
        cache.write_policy("default", "p", policy)
        assert cache.read_policy("default", "p").name == "p"
        cache.delete_policy("default", "p")
        with pytest.raises(CacheMissError):
            cache.read_policy("default", "p")

    def test_periodic_update_refreshes(self):
        """Values change after a ticker period (autoupdating_test.go:15-62)."""
        cache = AutoUpdatingCache()
        cache.write_metric("m", None)
        client = DummyMetricsClient({"m": {"n1": NodeMetric(value=Quantity("1"))}})
        stop = cache.start_periodic_update(0.02, client)
        try:
            deadline = time.time() + 2
            while time.time() < deadline:
                try:
                    if cache.read_metric("m")["n1"].value.cmp_int64(1) == 0:
                        break
                except CacheMissError:
                    pass
                time.sleep(0.01)
            assert cache.read_metric("m")["n1"].value.cmp_int64(1) == 0
            # now the backend changes; cache must follow
            client.store["m"] = {"n1": NodeMetric(value=Quantity("5"))}
            deadline = time.time() + 2
            while time.time() < deadline:
                if cache.read_metric("m")["n1"].value.cmp_int64(5) == 0:
                    break
                time.sleep(0.01)
            assert cache.read_metric("m")["n1"].value.cmp_int64(5) == 0
        finally:
            stop.set()

    def test_mirror_hooks_fire(self):
        cache = AutoUpdatingCache()
        events = []
        cache.on_metric_write.append(lambda name, data: events.append(("w", name)))
        cache.on_metric_delete.append(lambda name: events.append(("d", name)))
        cache.write_metric("m", None)
        cache.write_metric("m", {"n": NodeMetric(value=Quantity("1"))})
        cache.delete_metric("m")
        assert events == [("w", "m"), ("w", "m"), ("d", "m")]


class TestMetricsClient:
    def test_wrap_metrics_default_window(self):
        info = wrap_metrics(
            {"items": [{"describedObject": {"kind": "Node", "name": "n1"},
                        "value": "50"}]}
        )
        assert info["n1"].window_seconds == 60.0
        assert info["n1"].value.cmp_int64(50) == 0

    def test_wrap_metrics_explicit_window(self):
        info = wrap_metrics(
            {"items": [{"describedObject": {"name": "n1"}, "windowSeconds": 30,
                        "value": "104857600000m"}]}
        )
        assert info["n1"].window_seconds == 30.0
        assert info["n1"].value.cmp_int64(104857600) == 0

    def test_custom_metrics_client_via_fake(self):
        fake = FakeKubeClient()
        fake.set_node_metric("health_metric", "node1", "0")
        fake.set_node_metric("health_metric", "node2", "1")
        client = CustomMetricsClient(fake)
        info = client.get_node_metric("health_metric")
        assert set(info) == {"node1", "node2"}

    def test_empty_items_error(self):
        client = CustomMetricsClient(FakeKubeClient())
        with pytest.raises(MetricsError, match="no metrics returned"):
            client.get_node_metric("missing")

    def test_dummy_client(self):
        client = DummyMetricsClient(instance_of_mock_metric_client_map())
        assert client.get_node_metric("dummyMetric1")["node A"].value.cmp_int64(100) == 0
        with pytest.raises(MetricsError):
            client.get_node_metric("other")

"""Solve observatory (docs/observability.md "Solve observatory"):

  * stage attribution — the timer's marks are exhaustive (per-sample
    stage sums land within 10% of the independently measured end-to-end
    total) and forced solves attribute every pipeline seam;
  * refresh churn — a metric's FIRST pass counts every present column
    (full churn), a byte-identical refresh counts zero, a delete counts
    the columns it tore down, and a drain resets the accumulator;
  * off-path neutrality — with no observatory enabled the verb
    responses are byte-identical on the wire to an enabled build
    (modulo X-Request-ID) and /metrics emits no pas_solve_* /
    pas_state_churn_* families at all;
  * /debug/solve — indexed, 404 when unwired, 405 on non-GET, and the
    200 payload carries stages + churn + the recompile watch, on both
    front-ends;
  * recompile watch — a diurnal twin run recompiles NOTHING after a
    full-period warmup (pas_xla_compiles_total flat);
  * perf ledger — measure -> anchor -> drift round-trips and a
    synthetic 20% stage regression is flagged;
  * causal spine — churn/solve events join /debug/explain chains by
    tick as "the world changed under you" context, and churn passes
    export anonymized into the flight recorder (format /3).
"""

import json

import numpy as np
import pytest

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import (
    DEBUG_ENDPOINTS,
    HTTPRequest,
)
from platform_aware_scheduling_tpu.ops import solveobs
from platform_aware_scheduling_tpu.ops.rules import OP_IDS
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.events import JOURNAL
from platform_aware_scheduling_tpu.utils.quantity import Quantity
from platform_aware_scheduling_tpu.utils.record import FORMAT, FlightRecorder
from wirehelpers import (
    get_request,
    post_bytes,
    raw_request,
    start_async,
    start_threaded,
)


@pytest.fixture(autouse=True)
def _observatory_off():
    """Every test starts and ends with the observatory disabled — the
    module-global gate must never leak between tests."""
    saved = solveobs.ACTIVE
    solveobs.ACTIVE = None
    yield
    solveobs.ACTIVE = saved


def info(**kv):
    return {node: NodeMetric(value=Quantity(v)) for node, v in kv.items()}


def attach_pair(node_capacity=8, metric_capacity=2):
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror(
        node_capacity=node_capacity, metric_capacity=metric_capacity
    )
    mirror.attach(cache)
    return cache, mirror


def verb_request(path, body):
    return HTTPRequest(
        method="POST",
        path=path,
        headers={"Content-Type": "application/json"},
        body=body,
    )


class TestSolveTimer:
    def test_marks_attribute_elapsed_and_done_commits(self):
        t = {"now": 0.0}
        obs = solveobs.SolveObservatory(capacity=4, clock=lambda: t["now"])
        timer = obs.begin("unit")
        t["now"] = 100e-6
        assert timer.mark("snapshot") == pytest.approx(100.0)
        t["now"] = 250e-6
        timer.mark("execute")
        t["now"] = 300e-6
        timer.mark("execute")  # repeat marks ACCUMULATE
        t["now"] = 310e-6
        total = timer.done(nodes=3)
        assert total == pytest.approx(310.0)
        (sample,) = obs.ring
        assert sample["kind"] == "unit"
        assert sample["stages"] == {"snapshot": 100.0, "execute": 200.0}
        assert sample["total_us"] == 310.0
        assert sample["nodes"] == 3
        stages = obs.to_json_dict()["stages"]
        assert stages["snapshot"]["count"] == 1
        assert stages["execute"]["mean"] == pytest.approx(200.0)

    def test_ring_is_bounded(self):
        obs = solveobs.SolveObservatory(capacity=3, clock=lambda: 0.0)
        for i in range(7):
            obs.begin("unit").done(i=i)
        assert [s["i"] for s in obs.ring] == [4, 5, 6]
        assert (
            obs.counters.get("pas_solve_samples_total", kind="counter") == 7
        )


class TestChurnAccounting:
    def test_first_pass_counts_every_present_column(self):
        cache, mirror = attach_pair()
        obs = solveobs.enable()
        obs.mirror = mirror
        cache.write_metric("load", info(a="1", b="2", c="3"))
        pending, world = mirror.drain_churn()
        assert pending == {"load": (3, False)}
        assert world == 3

    def test_byte_identical_refresh_counts_zero(self):
        cache, mirror = attach_pair()
        obs = solveobs.enable()
        obs.mirror = mirror
        cache.write_metric("load", info(a="1", b="2"))
        mirror.drain_churn()
        cache.write_metric("load", info(a="1", b="2"))
        pending, _world = mirror.drain_churn()
        assert pending == {"load": (0, False)}

    def test_partial_change_counts_moved_columns_only(self):
        cache, mirror = attach_pair()
        obs = solveobs.enable()
        obs.mirror = mirror
        cache.write_metric("load", info(a="1", b="2", c="3"))
        mirror.drain_churn()
        # one value moves, one column disappears -> 2 churned columns
        cache.write_metric("load", info(a="9", b="2"))
        pending, _world = mirror.drain_churn()
        assert pending == {"load": (2, False)}

    def test_delete_counts_torn_down_columns(self):
        cache, mirror = attach_pair()
        obs = solveobs.enable()
        obs.mirror = mirror
        cache.write_metric("load", info(a="1", b="2"))
        mirror.drain_churn()
        mirror.on_metric_delete("load")
        pending, _world = mirror.drain_churn()
        assert pending == {"load": (2, True)}
        # drain resets: nothing pending afterwards
        assert mirror.drain_churn()[0] == {}

    def test_no_accounting_while_disabled(self):
        cache, mirror = attach_pair()
        cache.write_metric("load", info(a="1", b="2"))
        mirror.on_metric_delete("load")
        assert mirror.drain_churn()[0] == {}

    def test_flush_publishes_histograms_spine_and_flight(self):
        cache, mirror = attach_pair()
        obs = solveobs.enable()
        obs.mirror = mirror
        exported = []

        class _Flight:
            def record_churn(self, metrics, rows, world, fraction):
                exported.append((metrics, rows, world, fraction))

        obs.flight = _Flight()
        JOURNAL.reset()
        try:
            cache.write_metric("load", info(a="1", b="2", c="3", d="4"))
            cache.write_metric("temp", info(a="5", b="6"))
            obs.flush_refresh_pass()
            churn = obs.churn_summary()
            assert churn["world"] == 4
            assert churn["passes"] == 1
            last = churn["last_pass"]
            assert last["metrics"]["load"]["rows"] == 4
            assert last["metrics"]["load"]["fraction"] == 1.0
            assert last["metrics"]["temp"]["rows"] == 2
            assert last["total_rows"] == 6
            # pass fraction = 6 changed / (4 world * 2 metrics)
            assert last["fraction"] == pytest.approx(0.75)
            assert exported == [(2, 6, 4, pytest.approx(0.75))]
            churn_events = [
                r for r in JOURNAL.snapshot() if r["kind"] == "churn"
            ]
            assert len(churn_events) == 1
            assert churn_events[0]["data"]["rows"] == 6
            text = obs.metrics_text()
            assert 'pas_state_churn_rows_bucket{metric="load"' in text
            assert "pas_state_churn_fraction_bucket" in text
            assert "pas_state_churn_passes_total 1" in text
            assert "pas_state_churn_rows_changed_total 6" in text
        finally:
            JOURNAL.reset()

    def test_flush_without_pending_records_no_pass(self):
        _cache, mirror = attach_pair()
        obs = solveobs.enable()
        obs.mirror = mirror
        obs.flush_refresh_pass()
        assert obs.churn_summary()["passes"] == 0


class TestStageAttribution:
    """Forced solves through the REAL pipeline: every sample's stage
    marks must sum to the measured end-to-end total within 10% (plus a
    tiny absolute floor for sub-50us samples on a noisy CPU clock)."""

    def _assert_exhaustive(self, sample):
        total = sample["total_us"]
        attributed = sum(sample["stages"].values())
        assert abs(attributed - total) <= 0.10 * total + 25.0, sample

    def test_ranking_and_view_samples_sum_to_total(self):
        ext, _names = build_extender(64, device=True)
        obs = solveobs.enable()
        view = ext.mirror.device_view()
        row = view.metric_index["load_metric"]
        op = OP_IDS["GreaterThan"]
        for _ in range(3):
            with ext.fastpath._lock:
                ext.fastpath._rank.clear()
            ext.fastpath._ranking(view, row, op)
        with ext.mirror._lock:
            ext.mirror._version += 1  # invalidate the memoized view
        ext.mirror.device_view()
        kinds = {s["kind"] for s in obs.ring}
        assert {"prioritize_rank", "view_build"} <= kinds
        for sample in obs.ring:
            self._assert_exhaustive(sample)
        rank = [s for s in obs.ring if s["kind"] == "prioritize_rank"][-1]
        # post-warmup ranking touches every seam but compile/transfer
        assert {"execute", "readback", "encode"} <= set(rank["stages"])
        stages = obs.to_json_dict()["stages"]
        assert stages["execute"]["count"] >= 3

    def test_instrumented_ranking_matches_uninstrumented(self):
        ext, _names = build_extender(32, device=True)
        view = ext.mirror.device_view()
        row = view.metric_index["load_metric"]
        op = OP_IDS["GreaterThan"]
        bare = ext.fastpath._ranking(view, row, op)
        solveobs.enable()
        with ext.fastpath._lock:
            ext.fastpath._rank.clear()
        timed = ext.fastpath._ranking(view, row, op)
        np.testing.assert_array_equal(bare, timed)


@pytest.mark.parametrize("front_end", ["threaded", "async"])
class TestDebugSolveEndpoint:
    def test_404_when_off(self, front_end):
        ext, _names = build_extender(8, device=True)
        server = (
            start_async(ext) if front_end == "async" else start_threaded(ext)
        )
        try:
            status, _, body = get_request(server.port, "/debug/solve")
            assert status == 404
            assert "solve observatory" in json.loads(body)["error"]
        finally:
            server.shutdown()

    def test_payload_after_solves(self, front_end):
        ext, names = build_extender(8, device=True)
        obs = solveobs.enable()
        obs.mirror = ext.mirror
        ext.solveobs = obs
        server = (
            start_async(ext) if front_end == "async" else start_threaded(ext)
        )
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            status, _, _ = raw_request(
                server.port, post_bytes("/scheduler/prioritize", body)
            )
            assert status == 200
            with ext.fastpath._lock:
                ext.fastpath._rank.clear()
            view = ext.mirror.device_view()
            ext.fastpath._ranking(
                view,
                view.metric_index["load_metric"],
                OP_IDS["GreaterThan"],
            )
            obs.flush_refresh_pass()
            status, headers, payload = get_request(
                server.port, "/debug/solve"
            )
            assert status == 200
            assert headers["content-type"] == "application/json"
            out = json.loads(payload)
            assert out["enabled"] is True
            assert out["samples"] >= 1
            assert set(out["stages"]) <= set(solveobs.STAGES)
            assert out["recent"][-1]["kind"] == "prioritize_rank"
            assert "churn" in out
            assert "prioritize_kernel" in out["compiles"]
            # POST against the GET-only endpoint must 405
            status, _, _ = raw_request(
                server.port, post_bytes("/debug/solve", b"{}")
            )
            assert status == 405
        finally:
            server.shutdown()

    def test_indexed(self, front_end):
        assert "/debug/solve" in {e["path"] for e in DEBUG_ENDPOINTS}


class TestOffPathNeutrality:
    def test_verb_responses_byte_identical_with_and_without_observatory(
        self,
    ):
        """The observatory must never touch a verb response: the same
        request against a disabled and an enabled build returns the
        same status, body, and headers (only X-Request-ID may
        differ)."""
        wire = {}
        for label in ("off", "on"):
            solveobs.ACTIVE = None
            ext, names = build_extender(12, device=True)
            if label == "on":
                obs = solveobs.enable()
                obs.mirror = ext.mirror
                ext.solveobs = obs
            server = start_threaded(ext)
            try:
                body = make_bodies(names, "nodenames", count=1)[0]
                wire[label] = {
                    path: raw_request(
                        server.port, post_bytes(path, body)
                    )
                    for path in (
                        "/scheduler/prioritize",
                        "/scheduler/filter",
                    )
                }
            finally:
                server.shutdown()
                solveobs.ACTIVE = None
        for path, (status, headers, body) in wire["off"].items():
            on_status, on_headers, on_body = wire["on"][path]
            assert status == on_status == 200
            assert body == on_body
            drop = "x-request-id"
            assert {k: v for k, v in headers.items() if k != drop} == {
                k: v for k, v in on_headers.items() if k != drop
            }

    def test_metrics_families_follow_the_observatory(self):
        ext, names = build_extender(8, device=True)
        body = make_bodies(names, "nodenames", count=1)[0]
        ext.prioritize(verb_request("/scheduler/prioritize", body))
        text = ext.metrics_text()
        assert "pas_solve_" not in text
        assert "pas_state_churn_" not in text
        obs = solveobs.enable()
        obs.mirror = ext.mirror
        with ext.fastpath._lock:
            ext.fastpath._rank.clear()
        view = ext.mirror.device_view()
        ext.fastpath._ranking(
            view, view.metric_index["load_metric"], OP_IDS["GreaterThan"]
        )
        # one refresh lands after enabling, so the flush has churn
        ext.cache.write_metric("churn_probe", info(**{names[0]: "1"}))
        obs.flush_refresh_pass()
        text = ext.metrics_text()
        assert 'pas_solve_stage_us_bucket{stage="execute"' in text
        assert "pas_solve_samples_total" in text
        assert "pas_state_churn_passes_total" in text
        # the page must stay a parseable exposition with the extra
        # families mixed in — and every family declared (the same gate
        # trace-lint holds live /metrics to)
        families = trace.parse_prometheus_text(text)
        assert families["pas_solve_stage_us"]["type"] == "histogram"
        assert families["pas_state_churn_fraction"]["type"] == "histogram"
        for family in families:
            assert family in trace.METRICS, f"undeclared {family!r}"


class TestRecompileWatch:
    def test_compile_counter_and_watch_registry(self):
        watches = {w.name for w in trace.JIT_WATCHES}
        assert "prioritize_kernel" in watches
        for watch in trace.JIT_WATCHES:
            assert watch.compile_count >= 0
            assert watch.cache_size() >= 0

    def test_diurnal_twin_zero_recompiles_after_warmup(self):
        """One full diurnal period warms every shape the scenario can
        present; the second identical period must compile NOTHING new
        (pas_xla_compiles_total flat) — the steady-state gate that keeps
        jit cache-key drift from silently re-tracing in production."""
        from platform_aware_scheduling_tpu.testing.twin import TwinCluster

        twin = TwinCluster(num_nodes=8, pods=8, replicas=1)
        obs = solveobs.enable()
        stack = twin.live()[0]
        obs.mirror = stack.mirror
        stack.cache.on_refresh_pass.append(obs.flush_refresh_pass)
        period = 12

        def load_at(t):
            phase = 2.0 * np.pi * (t % period) / period
            return {
                name: int(200 + 150 * np.sin(phase + i))
                for i, name in enumerate(twin.live_node_names())
            }

        for t in range(period):  # warmup: one full period
            twin.set_base_load(load_at(t))
            twin.tick()
        warm = {w.name: w.compile_count for w in trace.JIT_WATCHES}
        for t in range(period):  # identical second period
            twin.set_base_load(load_at(t))
            twin.tick()
        steady = {w.name: w.compile_count for w in trace.JIT_WATCHES}
        assert steady == warm
        # the same run measures the churn distribution the observatory
        # exists to expose: passes landed and the fraction is sane
        churn = obs.churn_summary()
        assert churn["passes"] > 0
        assert churn["world"] == 8
        assert 0.0 <= churn["fraction_mean"] <= 1.0
        assert churn["last_pass"]["total_rows"] >= 0


class TestPerfLedger:
    def test_round_trip_and_synthetic_regression_flagged(self, tmp_path):
        from benchmarks import perf_ledger

        measurement = perf_ledger.measure(
            num_nodes=48, solve_reps=6, verb_reps=40
        )
        entries = measurement["entries"]
        assert "solve_execute" in entries
        assert "warm_filter_verb" in entries
        for entry in entries.values():
            assert entry["floor_us"] > 0
            assert (
                perf_ledger.TOL_MIN_PCT
                <= entry["tolerance_pct"]
                <= perf_ledger.TOL_MAX_PCT
            )
        anchor_path = tmp_path / "anchor.json"
        anchor = perf_ledger.write_anchor(measurement, anchor_path)
        assert perf_ledger.load_anchor(anchor_path) == anchor
        # a measurement drifts zero against itself
        rows = perf_ledger.drift(measurement, anchor)
        assert rows and not any(r["flagged"] for r in rows)
        # a synthetic 20% regression on any one stage must flag: the
        # tolerance cap (15%) sits below it by construction
        import copy

        current = copy.deepcopy(measurement)
        current["entries"]["solve_execute"]["floor_us"] *= 1.20
        rows = perf_ledger.drift(current, anchor)
        flagged = [r["name"] for r in rows if r["flagged"]]
        assert flagged == ["solve_execute"]

    def test_one_sided_entries_never_flag(self):
        from benchmarks import perf_ledger

        anchor = {
            "entries": {"gone": {"floor_us": 10.0, "tolerance_pct": 10.0}}
        }
        current = {
            "entries": {"new": {"floor_us": 99.0, "tolerance_pct": 10.0}}
        }
        rows = perf_ledger.drift(current, anchor)
        assert {r["name"]: r["flagged"] for r in rows} == {
            "gone": False,
            "new": False,
        }

    def test_committed_anchor_is_loadable(self):
        from benchmarks import perf_ledger

        anchor = perf_ledger.load_anchor()
        assert anchor is not None, "benchmarks/perf_anchor.json missing"
        assert anchor["entries"], "committed anchor has no entries"


class TestCausalSpine:
    def test_churn_joins_explain_chain_by_tick(self):
        obs = solveobs.enable()
        JOURNAL.reset()
        saved_source = JOURNAL.tick_source
        JOURNAL.tick_source = lambda: 7
        try:
            JOURNAL.publish("verdict", "filter passed", pod="ns/p1")
            obs._publish_churn(2, 10, 50, 0.1)
            JOURNAL.tick_source = lambda: 8
            obs._publish_churn(1, 3, 50, 0.06)  # other tick: stays out
            out = JOURNAL.explain(pod="ns/p1")
            context = out["context"]
            assert [r["tick"] for r in context] == [7]
            assert context[0]["kind"] == "churn"
            assert context[0]["data"]["rows"] == 10
            assert any(
                "churn" in line for line in out["context_narrative"]
            )
            # churn events carry no entity keys -> never in the chain
            assert all(r["kind"] != "churn" for r in out["events"])
        finally:
            JOURNAL.tick_source = saved_source
            JOURNAL.reset()

    def test_warm_pass_publishes_solve_event(self):
        ext, _names = build_extender(8, device=True)
        JOURNAL.reset()
        try:
            ext.warm_fastpath()  # disabled: no event
            assert not [
                r for r in JOURNAL.snapshot() if r["kind"] == "solve"
            ]
            solveobs.enable()
            ext.warm_fastpath()
            (event,) = [
                r for r in JOURNAL.snapshot() if r["kind"] == "solve"
            ]
            assert event["event"] == "fastpath warmed"
            assert event["data"]["duration_us"] >= 0
            assert "pairs" in event["data"]
        finally:
            JOURNAL.reset()

    def test_flight_export_is_anonymous_and_versioned(self):
        assert FORMAT == "pas-flight-record/4"
        rec = FlightRecorder()
        rec.record_churn(3, 17, 100, 0.0567)
        (event,) = rec.events()
        assert event["kind"] == "churn"
        assert event["metrics"] == 3
        assert event["rows"] == 17
        assert event["world"] == 100
        assert event["fraction"] == pytest.approx(0.0567, abs=1e-4)
        # counts only — a capture never names a metric or node
        assert "load_metric" not in rec.to_jsonl().decode()


class TestAssembly:
    def test_flags_offered_on_both_mains(self):
        from platform_aware_scheduling_tpu.cmd import gas, tas

        for build in (tas.build_arg_parser, gas.build_arg_parser):
            args = build().parse_args([])
            assert args.solveObs == "off"
            args = build().parse_args(
                ["--solveObs", "on", "--solveObsSize", "64"]
            )
            assert args.solveObs == "on"
            assert args.solveObsSize == 64

    def test_build_wires_mirror_flight_and_refresh_hook(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        ext, _names = build_extender(8, device=True)
        ext.flight = FlightRecorder()
        parser = tas.build_arg_parser()
        args = parser.parse_args([])
        assert common.build_solve_observatory(args, ext) is None
        assert solveobs.ACTIVE is None
        args = parser.parse_args(["--solveObs", "on", "--solveObsSize", "64"])
        obs = common.build_solve_observatory(
            args, ext, cache=ext.cache
        )
        assert solveobs.ACTIVE is obs
        assert ext.solveobs is obs
        assert obs.capacity == 64
        assert obs.mirror is ext.mirror
        assert obs.flight is ext.flight
        assert obs.flush_refresh_pass in ext.cache.on_refresh_pass
        solveobs.disable()
        assert solveobs.ACTIVE is None

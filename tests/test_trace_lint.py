"""Metric-name convention gate (``make trace-lint``, wired into CI):
every metric this process can emit is declared once in trace.METRICS,
follows the ``pas_`` prefix + snake_case convention with the Prometheus
suffix rules, and live /metrics output contains ONLY declared families
whose TYPE matches the declaration.  A new metric that skips the
inventory fails here, not in a scrape dashboard three rounds later."""

import re

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.utils import trace

NAME_RE = re.compile(r"^pas_[a-z][a-z0-9]*(_[a-z0-9]+)*$")
KINDS = {"counter", "gauge", "histogram"}


class TestDeclaredInventory:
    def test_names_follow_convention(self):
        assert trace.METRICS, "the inventory must not be empty"
        for name, (kind, help_text) in trace.METRICS.items():
            assert NAME_RE.match(name), f"{name}: not pas_ snake_case"
            assert kind in KINDS, f"{name}: unknown kind {kind}"
            assert help_text.strip(), f"{name}: empty help text"

    def test_suffix_conventions(self):
        """Counters end in _total (Prometheus naming convention); gauges
        and histograms must NOT claim the counter suffix."""
        for name, (kind, _help) in trace.METRICS.items():
            if kind == "counter":
                assert name.endswith("_total"), f"{name}: counter sans _total"
            else:
                assert not name.endswith("_total"), (
                    f"{name}: _total reserved for counters"
                )

    def test_declare_rejects_redeclaration(self):
        import pytest

        with pytest.raises(ValueError):
            trace.declare("pas_request_duration_seconds", "counter", "dup")

    def test_control_plane_families_declared(self):
        """ISSUE 3: the health/telemetry/workqueue/informer/device
        families are part of the declared inventory (and therefore under
        every other convention check in this gate)."""
        expected = {
            "pas_ready": "gauge",
            "pas_ready_transitions_total": "counter",
            "pas_telemetry_metric_age_seconds": "gauge",
            "pas_telemetry_refresh_total": "counter",
            "pas_telemetry_refresh_errors_total": "counter",
            "pas_strategy_evaluations_total": "counter",
            "pas_strategy_violations_total": "counter",
            "pas_strategy_enforcements_total": "counter",
            "pas_workqueue_depth": "gauge",
            "pas_workqueue_adds_total": "counter",
            "pas_workqueue_retries_total": "counter",
            "pas_workqueue_done_total": "counter",
            "pas_informer_relists_total": "counter",
            "pas_informer_watch_errors_total": "counter",
            "pas_informer_synced": "gauge",
            "pas_device_memory_in_use_bytes": "gauge",
            "pas_device_memory_peak_bytes": "gauge",
            "pas_device_memory_limit_bytes": "gauge",
            "pas_device_kernel_flops": "gauge",
            "pas_device_kernel_bytes": "gauge",
            "pas_profile_captures_total": "counter",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_rebalance_families_declared(self):
        """ISSUE 4: the closed-loop rebalancer's metric families are part
        of the declared inventory (docs/rebalance.md)."""
        expected = {
            "pas_rebalance_plans_total": "counter",
            "pas_rebalance_moves_planned_total": "counter",
            "pas_rebalance_moves_executed_total": "counter",
            "pas_rebalance_moves_skipped_total": "counter",
            "pas_rebalance_candidate_nodes": "gauge",
            "pas_rebalance_convergence_cycles": "gauge",
            "pas_rebalance_plan_latency_seconds": "gauge",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_decision_families_declared(self):
        """ISSUE 6: the decision-provenance placement-quality families
        are part of the declared inventory (docs/observability.md
        "Decision provenance")."""
        expected = {
            "pas_decision_records_total": "counter",
            "pas_decision_filtered_nodes_total": "counter",
            "pas_decision_open": "gauge",
            "pas_decision_closed_total": "counter",
            "pas_decision_violated_at_bind_total": "counter",
            "pas_decision_chosen_rank_total": "counter",
            "pas_decision_evicted_open_total": "counter",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_gang_families_declared(self):
        """ISSUE 7: the gang-scheduling metric families are part of the
        declared inventory (docs/gang.md)."""
        expected = {
            "pas_gang_reservations_total": "counter",
            "pas_gang_reservation_expirations_total": "counter",
            "pas_gang_admitted_total": "counter",
            "pas_gang_rejected_total": "counter",
            "pas_gang_active": "gauge",
            "pas_gang_reserved_nodes": "gauge",
            "pas_gang_time_to_full_seconds": "histogram",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_forecast_families_declared(self):
        """ISSUE 8: the predictive-telemetry metric families are part of
        the declared inventory (docs/forecast.md)."""
        expected = {
            "pas_forecast_fit_passes_total": "counter",
            "pas_forecast_extrapolated_serves_total": "counter",
            "pas_forecast_suppressed_evictions_total": "counter",
            "pas_forecast_metric_slope": "gauge",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_ha_families_declared(self):
        """ISSUE 9: the leader-election + gang-journal metric families
        are part of the declared inventory (docs/robustness.md "HA &
        leader election")."""
        expected = {
            "pas_leader": "gauge",
            "pas_leader_transitions_total": "counter",
            "pas_gang_journal_writes_total": "counter",
            "pas_gang_journal_skipped_total": "counter",
            "pas_gang_journal_recovered_total": "counter",
            "pas_gang_journal_discarded_total": "counter",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_slo_families_declared(self):
        """ISSUE 10: the SLO engine's metric families are part of the
        declared inventory (docs/observability.md "SLOs & error
        budgets")."""
        expected = {
            "pas_slo_compliance": "gauge",
            "pas_slo_error_budget_remaining": "gauge",
            "pas_slo_burn_rate": "gauge",
            "pas_slo_breaches_total": "counter",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_wire_intern_families_declared(self):
        """ISSUE 11: the universe-interning counters are part of the
        declared inventory (docs/architecture.md "The wire path")."""
        expected = {
            "pas_wire_intern_hits_total": "counter",
            "pas_wire_intern_misses_total": "counter",
            "pas_wire_intern_evictions_total": "counter",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_record_families_declared(self):
        """ISSUE 13: the flight-recorder + what-if counter families are
        part of the declared inventory (docs/observability.md "Flight
        recorder & what-if")."""
        expected = {
            "pas_record_events_total": "counter",
            "pas_record_dropped_total": "counter",
            "pas_whatif_runs_total": "counter",
            "pas_whatif_failures_total": "counter",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name

    def test_fault_tolerance_families_declared(self):
        """ISSUE 5: the retry/circuit/degraded families are part of the
        declared inventory (docs/robustness.md)."""
        expected = {
            "pas_kube_retry_total": "counter",
            "pas_kube_giveup_total": "counter",
            "pas_circuit_state": "gauge",
            "pas_circuit_transitions_total": "counter",
            "pas_degraded": "gauge",
        }
        for name, kind in expected.items():
            assert name in trace.METRICS, f"{name} missing from inventory"
            assert trace.METRICS[name][0] == kind, name


class TestLiveEmission:
    """Drive both front-ends, scrape /metrics, and hold every emitted
    family against the declared inventory."""

    def _assert_only_declared(self, text: str) -> None:
        families = trace.parse_prometheus_text(text)
        assert families, "live /metrics must not be empty"
        for family, data in families.items():
            assert family in trace.METRICS, f"undeclared metric {family!r}"
            declared_kind, _help = trace.METRICS[family]
            assert data["type"] == declared_kind, (
                f"{family}: emitted TYPE {data['type']} != declared "
                f"{declared_kind}"
            )
            for name, _labels, _value in data["samples"]:
                base = family if name.startswith(family) else name
                assert NAME_RE.match(base), f"sample {name!r} off-convention"

    def test_threaded_front_end_emits_declared_names_only(self):
        ext, names = build_extender(48, device=True)
        body = make_bodies(names, "nodenames", count=1)[0]
        for path in ("/scheduler/prioritize", "/scheduler/filter"):
            ext.__getattribute__(path.rsplit("/", 1)[1])(
                HTTPRequest(
                    method="POST",
                    path=path,
                    headers={"Content-Type": "application/json"},
                    body=body,
                )
            )
        self._assert_only_declared(ext.metrics_text())

    def test_async_front_end_emits_declared_names_only(self):
        from wirehelpers import post_bytes, raw_request, start_async

        ext, names = build_extender(48, device=True)
        server = start_async(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            status, _, _ = raw_request(
                server.port, post_bytes("/scheduler/prioritize", body)
            )
            assert status == 200
            text = server._router.metrics_provider()
            self._assert_only_declared(text)
        finally:
            server.shutdown()

    def test_health_and_device_families_emit_declared_names_only(self):
        """Readiness evaluations + device watermark/cost gauges land on
        the same exposition and stay inside the inventory — labels and
        all (the parser separates them; the base family must be
        declared)."""
        from platform_aware_scheduling_tpu.utils import health

        ext, names = build_extender(48, device=True)
        probe = health.probe_for(ext)
        probe.evaluate()  # pas_ready (+ transitions on later flips)
        # a labeled device gauge without real accelerator stats: exported
        # through the same CounterSet path the real sampler uses
        trace.COUNTERS.set_gauge(
            "pas_device_kernel_flops", 123.0,
            labels={"kernel": "lint_probe_kernel"},
        )
        body = make_bodies(names, "nodenames", count=1)[0]
        ext.prioritize(
            HTTPRequest(
                method="POST",
                path="/scheduler/prioritize",
                headers={"Content-Type": "application/json"},
                body=body,
            )
        )
        text = ext.metrics_text()
        self._assert_only_declared(text)
        families = trace.parse_prometheus_text(text)
        assert "pas_ready" in families
        assert "pas_device_kernel_flops" in families

    def test_gas_extender_emits_declared_names_only(self):
        from platform_aware_scheduling_tpu.gas.scheduler import GASExtender
        from platform_aware_scheduling_tpu.testing.fake_kube import (
            FakeKubeClient,
        )

        ext = GASExtender(FakeKubeClient(), use_device=False)
        ext.filter(
            HTTPRequest(
                method="POST",
                path="/scheduler/filter",
                headers={"Content-Type": "application/json"},
                body=b"{}",
            )
        )
        self._assert_only_declared(ext.metrics_text())

"""Oracle pack (testing/oracles.py; docs/robustness.md "Adversarial
scenario search"): the no-false-positive pin — every hand-scripted
scenario stays green with the full pack riding along — plus the
quiet-timeline pin and per-oracle fire tests through the fuzzer's
planted bugs.  An oracle that pages on a healthy timeline is a defect
in the oracle; these tests are the contract that keeps the fuzzer's
finds meaningful."""

import pytest

from platform_aware_scheduling_tpu.testing import fuzz, oracles
from platform_aware_scheduling_tpu.testing import twin as tw
from platform_aware_scheduling_tpu.utils.events import JOURNAL

CORE_SCALE = {
    "num_nodes": 16,
    "pods": 16,
    "period_s": 5.0,
    "requests_per_tick": 1,
}
CONTROL_SCALE = {"num_nodes": 16, "pods": 16, "period_s": 5.0}
ADMISSION_SCALE = {"period_s": 5.0}

#: every hand-scripted scenario program, with the scale its own harness
#: runs it at (scenario objects carry per-run state: factories, not
#: instances)
SCENARIO_MATRIX = [
    (lambda: tw.DiurnalLoad(), CORE_SCALE),
    (lambda: tw.DeploymentWave(), CORE_SCALE),
    (lambda: tw.NodeFailureWave(), CORE_SCALE),
    (lambda: tw.MetricStorm(), CORE_SCALE),
    (lambda: tw.LeaderKillComposite(), CORE_SCALE),
    (lambda: tw.PartitionHandoff(), CORE_SCALE),
    (lambda: tw.GangWave(), CORE_SCALE),
    (lambda: tw.ControlMetricStorm(control=False), CONTROL_SCALE),
    (lambda: tw.ControlMetricStorm(control=True), CONTROL_SCALE),
    (lambda: tw.ControlDeploymentWave(control=False), CONTROL_SCALE),
    (lambda: tw.ControlDeploymentWave(control=True), CONTROL_SCALE),
    (lambda: tw.PriorityInversionStorm(), ADMISSION_SCALE),
    (lambda: tw.BackfillStarvation(), ADMISSION_SCALE),
    (lambda: tw.PreemptionCascade(preemption=True), ADMISSION_SCALE),
    (lambda: tw.PreemptionCascade(preemption=False), ADMISSION_SCALE),
]


def _ids():
    return [factory().name for factory, _scale in SCENARIO_MATRIX]


@pytest.fixture(autouse=True)
def _clean_journal():
    JOURNAL.reset()
    yield
    JOURNAL.reset()


def _oracle_failures(result):
    return [c for c in result["oracle_checks"] if not c["ok"]]


class TestNoFalsePositives:
    """The pin the whole fuzzing layer rests on: the full pack is
    silent on every healthy hand-scripted timeline.  A single false
    positive here and every fuzzer find needs manual triage."""

    @pytest.mark.parametrize(
        "factory,scale", SCENARIO_MATRIX, ids=_ids()
    )
    def test_scenario_green_with_the_pack_attached(self, factory, scale):
        result = oracles.run_scenario(factory(), dict(scale))
        assert result["passed"], [
            c for c in result["checks"] if not c["ok"]
        ]
        assert result["oracles_ok"], _oracle_failures(result)


class TestQuietTimeline:
    def test_quiet_pack_is_green_on_a_quiet_day(self):
        pack = oracles.OraclePack(quiet=True)
        result = oracles.run_scenario(
            tw.DiurnalLoad(), dict(CORE_SCALE), pack=pack
        )
        assert result["oracles_ok"], _oracle_failures(result)
        assert any(
            c["check"] == "oracle:quiet" for c in result["oracle_checks"]
        )

    def test_quiet_oracle_fires_on_an_actuating_timeline(self):
        """Declaring a deployment wave quiet must fail loudly: the wave
        evicts, and the zero-actuation pin calls it."""
        pack = oracles.OraclePack(quiet=True)
        result = oracles.run_scenario(
            tw.DeploymentWave(), dict(CORE_SCALE), pack=pack
        )
        failed = {c["check"] for c in _oracle_failures(result)}
        assert "oracle:quiet" in failed


class TestOraclesFire:
    """Each oracle's detection direction, demonstrated through the
    fuzzer's planted bugs (the same ground truth ``make fuzz-smoke``
    gates on) or a tightened bound — an oracle that can't fire proves
    nothing by staying green."""

    def test_population_fires_on_a_lost_rebind(self):
        with fuzz.planted_bug("lost_rebind"):
            result = oracles.run_scenario(
                tw.DeploymentWave(), dict(CORE_SCALE)
            )
        failed = {c["check"] for c in _oracle_failures(result)}
        assert "oracle:population" in failed

    def test_shard_splice_fires_on_a_broken_store(self):
        scenario = tw.load_scenario(
            "tests/scenarios/stale_digest_splice.json"
        )
        with fuzz.planted_bug("stale_digest_splice"):
            record = fuzz.run_candidate(scenario.genome)
        assert "oracle:shard_splice" in record["failures"]

    def test_preemption_progress_fires_past_a_tight_k(self):
        """K=0 turns any legitimate eviction into a violation — the
        bound really is counting per-pod evictions."""
        pack = oracles.OraclePack(
            [oracles.PreemptionProgress(k=0)]
        )
        result = oracles.run_scenario(
            tw.DeploymentWave(), dict(CORE_SCALE), pack=pack
        )
        failed = {c["check"] for c in _oracle_failures(result)}
        assert "oracle:preemption_progress" in failed

    def test_shard_oracles_stay_out_of_unsharded_runs(self):
        """On a twin with no shard plane the shard oracles emit NO
        checks at all (absence, not vacuous green) — coverage signals
        must reflect what a candidate actually exercised."""
        result = oracles.run_scenario(
            tw.DiurnalLoad(), dict(CORE_SCALE)
        )
        names = {c["check"] for c in result["oracle_checks"]}
        assert "oracle:shard_epoch" not in names
        assert "oracle:shard_splice" not in names
        # sharded runs DO emit them
        sharded = oracles.run_scenario(
            tw.PartitionHandoff(), dict(CORE_SCALE)
        )
        sharded_names = {c["check"] for c in sharded["oracle_checks"]}
        assert "oracle:shard_epoch" in sharded_names
        assert "oracle:shard_splice" in sharded_names

"""BatchPlanner: pending-set maintenance, batch solve, plan serving, and
the prioritize steering path."""

import json
import time

import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.planner import BatchPlanner
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import (
    make_policy,
    make_pod,
    rule,
)
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def metric_info(**kv):
    return {n: NodeMetric(value=Quantity(str(v))) for n, v in kv.items()}


def build(node_capacity=1):
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    planner = BatchPlanner(cache, mirror, node_capacity=node_capacity)
    cache.write_policy(
        "default",
        "plan-pol",
        TASPolicy.from_obj(
            make_policy(
                "plan-pol",
                strategies={
                    "scheduleonmetric": [rule("m", "GreaterThan", 0)],
                    "dontschedule": [rule("m", "GreaterThan", 900)],
                },
            )
        ),
    )
    cache.write_metric("m", metric_info(n1=100, n2=50, n3=10))
    return cache, mirror, planner


def pending_pod(name):
    return make_pod(name, labels={"telemetry-policy": "plan-pol"})


class TestReplan:
    def test_capacity_one_spreads_pods(self):
        _, _, planner = build(node_capacity=1)
        for i in range(3):
            planner.pod_added(pending_pod(f"p{i}"))
        assert planner.replan() == 3
        nodes = {
            planner.planned_node(pending_pod(f"p{i}")) for i in range(3)
        }
        # greedy-in-order: p0 gets n1 (100), p1 n2 (50), p2 n3 (10)
        assert planner.planned_node(pending_pod("p0")) == "n1"
        assert planner.planned_node(pending_pod("p1")) == "n2"
        assert planner.planned_node(pending_pod("p2")) == "n3"
        assert nodes == {"n1", "n2", "n3"}

    def test_dontschedule_respected(self):
        cache, _, planner = build(node_capacity=5)
        cache.write_metric("m", metric_info(n1=1000, n2=50, n3=10))
        planner.pod_added(pending_pod("p0"))
        planner.replan()
        # n1 violates (1000 > 900): best eligible is n2
        assert planner.planned_node(pending_pod("p0")) == "n2"

    def test_bound_pod_leaves_plan(self):
        _, _, planner = build()
        planner.pod_added(pending_pod("p0"))
        planner.replan()
        assert planner.planned_node(pending_pod("p0")) == "n1"
        planner.pod_bound(pending_pod("p0"))
        assert planner.planned_node(pending_pod("p0")) is None

    def test_stale_plan_invalidated_by_state_change(self):
        cache, mirror, planner = build()
        planner.pod_added(pending_pod("p0"))
        planner.replan()
        assert planner.planned_node(pending_pod("p0")) == "n1"
        cache.write_metric("m", metric_info(n1=1, n2=50, n3=10))
        assert planner.planned_node(pending_pod("p0")) is None
        planner.replan()
        assert planner.planned_node(pending_pod("p0")) == "n2"

    def test_unlabelled_or_bound_pods_ignored(self):
        _, _, planner = build()
        planner.pod_added(make_pod("nolabel"))
        planner.pod_added(make_pod("bound", labels={"telemetry-policy": "x"},
                                   node_name="n1"))
        assert planner.pending_count() == 0


class TestPrioritizeSteering:
    def _request(self, pod_name):
        return HTTPRequest(
            method="POST",
            path="/scheduler/prioritize",
            headers={"Content-Type": "application/json"},
            body=json.dumps({
                "Pod": pending_pod(pod_name).raw,
                "Nodes": {"items": [
                    {"metadata": {"name": n}} for n in ("n1", "n2", "n3")
                ]},
            }).encode(),
        )

    def test_planned_node_promoted(self):
        cache, mirror, planner = build(node_capacity=1)
        ext = MetricsExtender(cache, mirror=mirror, planner=planner)
        for i in range(2):
            planner.pod_added(pending_pod(f"p{i}"))
        planner.replan()
        # p1's batch node is n2 even though n1 scores higher individually
        out = json.loads(ext.prioritize(self._request("p1")).body)
        assert out[0] == {"Host": "n2", "Score": 10}
        assert [e["Score"] for e in out] == [10, 9, 8]
        # p0 keeps n1 on top; unplanned pods get the plain ordering
        out0 = json.loads(ext.prioritize(self._request("p0")).body)
        assert out0[0] == {"Host": "n1", "Score": 10}
        outx = json.loads(ext.prioritize(self._request("ghost")).body)
        assert outx[0] == {"Host": "n1", "Score": 10}

    def test_planner_off_is_reference_behavior(self):
        cache, mirror, _ = build()
        ext = MetricsExtender(cache, mirror=mirror, planner=None)
        out = json.loads(ext.prioritize(self._request("p1")).body)
        assert out[0] == {"Host": "n1", "Score": 10}


class TestWatchFeed:
    def test_informer_feeds_pending_set(self):
        cache, mirror, planner = build()
        kube = FakeKubeClient()
        informer = planner.watch(kube)
        try:
            kube.add_pod(pending_pod("w0"))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and planner.pending_count() == 0:
                time.sleep(0.02)
            assert planner.pending_count() == 1
            bound = pending_pod("w0")
            bound.raw["spec"]["nodeName"] = "n1"
            bound.metadata["resourceVersion"] = "9"
            kube.update_pod(bound)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and planner.pending_count() > 0:
                time.sleep(0.02)
            assert planner.pending_count() == 0
        finally:
            informer.stop()


class TestCapacityFidelity:
    def test_full_node_stops_receiving_assignments(self):
        """A node with no remaining pod slots (allocatable − bound == 0) must
        not receive plan assignments, however well it scores."""
        _, _, planner = build(node_capacity=5)
        from platform_aware_scheduling_tpu.testing.builders import make_node

        planner.node_changed(make_node("n1", allocatable={"pods": "2"}))
        planner.pod_observed(make_pod("b0", node_name="n1"))
        planner.pod_observed(make_pod("b1", node_name="n1"))
        planner.pod_added(pending_pod("p0"))
        assert planner.replan() == 1
        assert planner.planned_node(pending_pod("p0")) == "n2"

    def test_terminated_pod_frees_its_slot(self):
        _, _, planner = build(node_capacity=5)
        from platform_aware_scheduling_tpu.testing.builders import make_node

        planner.node_changed(make_node("n1", allocatable={"pods": "1"}))
        bound = make_pod("b0", node_name="n1")
        planner.pod_observed(bound)
        planner.pod_added(pending_pod("p0"))
        planner.replan()
        assert planner.planned_node(pending_pod("p0")) == "n2"
        done = make_pod("b0", node_name="n1", phase="Succeeded")
        planner.pod_observed(done)
        planner.replan()
        assert planner.planned_node(pending_pod("p0")) == "n1"

    def test_unobserved_nodes_fall_back_to_default(self):
        """Nodes with no observed allocatable keep the kubelet-default
        fallback, so behavior without informers matches round 1."""
        _, _, planner = build(node_capacity=1)
        for i in range(3):
            planner.pod_added(pending_pod(f"p{i}"))
        assert planner.replan() == 3

    def test_node_informer_feeds_allocatable(self):
        from platform_aware_scheduling_tpu.testing.builders import make_node

        cache, mirror, planner = build(node_capacity=5)
        kube = FakeKubeClient()
        kube.add_node(make_node("n1", allocatable={"pods": "0"}))
        handle = planner.watch(kube)
        try:
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline and "n1" not in planner._node_alloc:
                time.sleep(0.02)
            assert planner._node_alloc.get("n1") == 0
            planner.pod_added(pending_pod("p0"))
            planner.replan()
            assert planner.planned_node(pending_pod("p0")) == "n2"
        finally:
            handle.stop()


class TestSinkhornPlanner:
    def test_sinkhorn_solver_coordinates(self):
        cache = AutoUpdatingCache()
        mirror = TensorStateMirror()
        mirror.attach(cache)
        planner = BatchPlanner(cache, mirror, node_capacity=1,
                               solver="sinkhorn")
        cache.write_policy(
            "default", "plan-pol",
            TASPolicy.from_obj(make_policy("plan-pol", strategies={
                "scheduleonmetric": [rule("m", "GreaterThan", 0)]})),
        )
        cache.write_metric("m", metric_info(n1=100, n2=99))
        planner.pod_added(pending_pod("p0"))
        planner.pod_added(pending_pod("p1"))
        assert planner.replan() == 2
        placed = {planner.planned_node(pending_pod("p0")),
                  planner.planned_node(pending_pod("p1"))}
        assert placed == {"n1", "n2"}

"""Quantity semantics parity with k8s resource.Quantity
(reference operator.go CmpInt64 usage; gpuscheduler AsInt64 usage)."""

from fractions import Fraction

import pytest

from platform_aware_scheduling_tpu.utils.quantity import (
    Quantity,
    QuantityParseError,
)


class TestParse:
    @pytest.mark.parametrize(
        "text,expected",
        [
            ("0", 0),
            ("1", 1),
            ("-1", -1),
            ("+5", 5),
            ("100", 100),
            ("9999", 9999),
            ("1k", 1000),
            ("1M", 10**6),
            ("1G", 10**9),
            ("1T", 10**12),
            ("1P", 10**15),
            ("1E", 10**18),
            ("1Ki", 1024),
            ("1Mi", 1024**2),
            ("1Gi", 1024**3),
            ("1Ti", 1024**4),
            ("128Mi", 128 * 1024**2),
            ("500m", Fraction(1, 2)),
            ("250m", Fraction(1, 4)),
            ("100u", Fraction(1, 10**4)),
            ("100n", Fraction(1, 10**7)),
            ("1e3", 1000),
            ("1E3", 1000),
            ("1e-3", Fraction(1, 1000)),
            ("2.5", Fraction(5, 2)),
            ("2.5Gi", Fraction(5, 2) * 1024**3),
            ("0.1", Fraction(1, 10)),
            (".5", Fraction(1, 2)),
            ("5.", 5),
            ("-500m", Fraction(-1, 2)),
            ("104857600000m", 104857600),
        ],
    )
    def test_parse_values(self, text, expected):
        assert Quantity(text).value == Fraction(expected)

    @pytest.mark.parametrize("text", ["", "abc", "1X", "--1", "1.2.3", "Ki", "1 Ki", "e3"])
    def test_parse_errors(self, text):
        with pytest.raises(QuantityParseError):
            Quantity(text)

    def test_parse_int_and_float(self):
        assert Quantity(42).value == 42
        assert Quantity(0.5).value == Fraction(1, 2)


class TestCmp:
    def test_cmp_int64(self):
        assert Quantity("100").cmp_int64(100) == 0
        assert Quantity("99").cmp_int64(100) == -1
        assert Quantity("101").cmp_int64(100) == 1
        # milli-precision comparisons are exact
        assert Quantity("100001m").cmp_int64(100) == 1
        assert Quantity("99999m").cmp_int64(100) == -1
        assert Quantity("100000m").cmp_int64(100) == 0

    def test_cmp_quantity(self):
        assert Quantity("1Gi").cmp(Quantity("1G")) == 1  # 1073741824 > 1e9
        assert Quantity("500m").cmp(Quantity("0.5")) == 0
        assert Quantity("1").cmp(Quantity("2")) == -1

    def test_cmp_huge(self):
        huge = str(2**63 - 1)
        assert Quantity(huge).cmp_int64(2**63 - 1) == 0
        assert Quantity(huge + "000m").cmp_int64(2**63 - 1) == 0


class TestAccessors:
    def test_as_int64(self):
        assert Quantity("5").as_int64() == (5, True)
        assert Quantity("1Ki").as_int64() == (1024, True)
        # fractional value: (0, False) like Go AsInt64
        assert Quantity("500m").as_int64() == (0, False)
        # out of range
        assert Quantity(str(2**64)).as_int64() == (0, False)

    def test_milli_value_exact(self):
        assert Quantity("5").milli_value_exact() == (5000, True)
        assert Quantity("500m").milli_value_exact() == (500, True)
        v, exact = Quantity("1u").milli_value_exact()  # sub-milli -> inexact
        assert not exact and v == 0
        v, exact = Quantity(str(2**63)).milli_value_exact()  # overflow clamps
        assert not exact and v == 2**63 - 1

    def test_as_dec(self):
        assert Quantity("1Ki").as_dec() == "1024"
        assert Quantity("5").as_dec() == "5"

    def test_str_roundtrip(self):
        assert str(Quantity("128Mi")) == "128Mi"

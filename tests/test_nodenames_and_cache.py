"""nodeCacheCapable wire mode + response-reuse caches: byte parity with
the exact Python paths, staleness safety, and slim-HTTP edge cases."""

import json
import socket

import numpy as np
import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest, Server
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils.quantity import Quantity

wirec = get_wirec()


def build(node_cache_capable=True, values=None, dontschedule_target=75):
    values = values or {"n1": 100, "n2": 50, "n3": 10, "n4": 70}
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default",
        "pol",
        TASPolicy.from_obj(
            make_policy(
                "pol",
                strategies={
                    "scheduleonmetric": [rule("m", "GreaterThan", 0)],
                    "dontschedule": [
                        rule("m", "GreaterThan", dontschedule_target)
                    ],
                },
            )
        ),
    )
    cache.write_metric(
        "m", {n: NodeMetric(value=Quantity(str(v))) for n, v in values.items()}
    )
    return cache, MetricsExtender(
        cache, mirror=mirror, node_cache_capable=node_cache_capable
    )


def req(path, body):
    return HTTPRequest(
        method="POST",
        path=path,
        headers={"Content-Type": "application/json"},
        body=body,
    )


def nn_body(names, pod="p"):
    return json.dumps(
        {
            "Pod": {
                "metadata": {
                    "name": pod,
                    "namespace": "default",
                    "labels": {"telemetry-policy": "pol"},
                }
            },
            "NodeNames": names,
        }
    ).encode()


def nodes_body(names, pod="p"):
    return json.dumps(
        {
            "Pod": {
                "metadata": {
                    "name": pod,
                    "namespace": "default",
                    "labels": {"telemetry-policy": "pol"},
                }
            },
            "Nodes": {"items": [{"metadata": {"name": n}} for n in names]},
        }
    ).encode()


class TestNodeNamesMode:
    def test_prioritize_serves_node_names(self):
        _, ext = build()
        resp = ext.prioritize(req("/scheduler/prioritize", nn_body(["n1", "n3", "n2"])))
        assert resp.status == 200
        scored = json.loads(resp.body)
        assert [e["Host"] for e in scored] == ["n2", "n3"] or [
            e["Host"] for e in scored
        ] == ["n1", "n2", "n3"]
        # n1=100 violates dontschedule>75? No: dontschedule only affects
        # Filter, not Prioritize (reference semantics) -> n1 first
        assert scored[0]["Host"] == "n1"
        assert scored[0]["Score"] == 10

    def test_native_equals_python_nodenames(self, monkeypatch):
        _, ext = build()
        for names in (["n1", "n2", "n3", "n4"], ["n4", "ghost"], []):
            body = nn_body(names)
            native = ext.prioritize(req("/scheduler/prioritize", body))
            monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
            python = ext.prioritize(req("/scheduler/prioritize", body))
            monkeypatch.delenv("PAS_TPU_NO_NATIVE")
            assert native.status == python.status, names
            assert native.body == python.body, names

    def test_quirk_preserved_when_capability_off(self):
        # reference TAS ignores NodeNames entirely: empty 200
        _, ext = build(node_cache_capable=False)
        resp = ext.prioritize(req("/scheduler/prioritize", nn_body(["n1"])))
        assert resp.status == 200
        assert resp.body == b""

    def test_filter_node_names_mode(self):
        _, ext = build()
        resp = ext.filter(req("/scheduler/filter", nn_body(["n1", "n2", "n3"])))
        assert resp.status == 200
        result = json.loads(resp.body)
        # n1=100 > 75 violates; n2/n3 pass.  No trailing "": in
        # nodeCacheCapable mode the scheduler consumes NodeNames and
        # rejects names outside its input list (the split-quirk stays
        # confined to the legacy Nodes branch).
        assert result["Nodes"] is None
        assert result["NodeNames"] == ["n2", "n3"]
        assert result["FailedNodes"] == {
            "n1": "policy pol: metric m=100 > threshold 75"
        }

    def test_filter_node_names_all_violating_is_empty_list(self):
        _, ext = build(dontschedule_target=5)  # every node violates
        resp = ext.filter(req("/scheduler/filter", nn_body(["n1", "n2", "n3"])))
        assert resp.status == 200
        result = json.loads(resp.body)
        assert result["NodeNames"] == []  # not [""]
        assert set(result["FailedNodes"]) == {"n1", "n2", "n3"}

    def test_filter_device_error_degrades_to_exact_path(self, monkeypatch):
        # a device/JAX runtime error in the cache probe (not just
        # ValueError/TypeError) must fall back to the exact path, never
        # surface as a 500 (round-3 advisor finding)
        _, ext = build()

        class XlaRuntimeError(Exception):
            pass

        monkeypatch.setattr(
            ext.fastpath,
            "violation_reasons",
            lambda *a, **k: (_ for _ in ()).throw(XlaRuntimeError("oom")),
        )
        resp = ext.filter(req("/scheduler/filter", nn_body(["n1", "n2", "n3"])))
        assert resp.status == 200
        result = json.loads(resp.body)
        assert result["NodeNames"] == ["n2", "n3"]
        assert result["FailedNodes"] == {
            "n1": "policy pol: metric m=100 > threshold 75"
        }

    def test_nodes_takes_precedence_over_nodenames(self, monkeypatch):
        _, ext = build()
        body = json.dumps(
            {
                "Pod": {
                    "metadata": {
                        "namespace": "default",
                        "labels": {"telemetry-policy": "pol"},
                    }
                },
                "Nodes": {"items": [{"metadata": {"name": "n2"}}]},
                "NodeNames": ["n1", "n3"],
            }
        ).encode()
        native = ext.prioritize(req("/scheduler/prioritize", body))
        assert [e["Host"] for e in json.loads(native.body)] == ["n2"]
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(req("/scheduler/prioritize", body))
        assert native.body == python.body


@pytest.mark.skipif(wirec is None, reason="no C toolchain for _wirec")
class TestResponseReuseCache:
    def test_rotating_pods_hit_cache_with_identical_bytes(self):
        _, ext = build()
        names = ["n1", "n2", "n3", "n4"]
        first = ext.prioritize(req("/scheduler/prioritize", nn_body(names, pod="a")))
        assert len(ext.fastpath._responses) == 1
        second = ext.prioritize(req("/scheduler/prioritize", nn_body(names, pod="b")))
        assert second.body == first.body
        assert len(ext.fastpath._responses) == 1  # reused, not re-stored

    def test_different_candidates_not_conflated(self):
        _, ext = build()
        a = ext.prioritize(req("/scheduler/prioritize", nn_body(["n1", "n2"])))
        b = ext.prioritize(req("/scheduler/prioritize", nn_body(["n3", "n4"])))
        assert a.body != b.body
        hosts_b = [e["Host"] for e in json.loads(b.body)]
        assert set(hosts_b) == {"n3", "n4"}

    def test_metric_update_invalidates_prioritize_cache(self):
        cache, ext = build()
        names = ["n1", "n2", "n3"]
        before = ext.prioritize(req("/scheduler/prioritize", nn_body(names)))
        assert json.loads(before.body)[0]["Host"] == "n1"
        cache.write_metric(
            "m",
            {
                "n1": NodeMetric(value=Quantity("1")),
                "n2": NodeMetric(value=Quantity("999")),
                "n3": NodeMetric(value=Quantity("5")),
            },
        )
        after = ext.prioritize(req("/scheduler/prioritize", nn_body(names)))
        assert json.loads(after.body)[0]["Host"] == "n2"

    def test_filter_cache_hits_and_invalidates(self, monkeypatch):
        cache, ext = build()
        names = ["n1", "n2", "n3"]
        body = nn_body(names)
        first = ext.filter(req("/scheduler/filter", body))
        assert json.loads(first.body)["FailedNodes"] == {
            "n1": "policy pol: metric m=100 > threshold 75"
        }
        assert len(ext.fastpath._filter_responses) == 1
        # second request (different pod) hits the cache byte-for-byte
        second = ext.filter(req("/scheduler/filter", nn_body(names, pod="q")))
        assert second.body == first.body
        # python path agrees
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.filter(req("/scheduler/filter", body))
        monkeypatch.delenv("PAS_TPU_NO_NATIVE")
        assert python.body == first.body
        # metric change flips the violation set -> fresh bytes
        cache.write_metric(
            "m",
            {
                "n1": NodeMetric(value=Quantity("1")),
                "n2": NodeMetric(value=Quantity("999")),
                "n3": NodeMetric(value=Quantity("5")),
            },
        )
        third = ext.filter(req("/scheduler/filter", body))
        assert json.loads(third.body)["FailedNodes"] == {
            "n2": "policy pol: metric m=999 > threshold 75"
        }

    def test_filter_nodes_mode_cache_parity(self, monkeypatch):
        cache, ext = build()
        names = ["n1", "n2", "n3"]
        body1 = nodes_body(names, pod="a")
        body2 = nodes_body(names, pod="b")
        first = ext.filter(req("/scheduler/filter", body1))
        second = ext.filter(req("/scheduler/filter", body2))
        assert second.body == first.body
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.filter(req("/scheduler/filter", body1))
        assert python.body == first.body


class TestSlimHTTPServer:
    def _serve(self):
        _, ext = build()
        server = Server(ext)
        server.start_server(port="0", unsafe=True, host="127.0.0.1", block=False)
        server.wait_ready()
        return server

    def test_pipelined_requests(self):
        server = self._serve()
        try:
            body = nn_body(["n1", "n2"])
            head = (
                f"POST /scheduler/prioritize HTTP/1.1\r\n"
                f"Host: x\r\nContent-Type: application/json\r\n"
                f"Content-Length: {len(body)}\r\n\r\n"
            ).encode()
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(head + body + head + body)  # two pipelined requests
            data = b""
            while data.count(b"HTTP/1.1 200") < 2:
                chunk = sock.recv(65536)
                assert chunk, data[:200]
                data += chunk
            sock.close()
        finally:
            server.shutdown()

    def test_expect_100_continue(self):
        server = self._serve()
        try:
            body = nn_body(["n1"])
            head = (
                f"POST /scheduler/prioritize HTTP/1.1\r\n"
                f"Host: x\r\nContent-Type: application/json\r\n"
                f"Expect: 100-continue\r\n"
                f"Content-Length: {len(body)}\r\n\r\n"
            ).encode()
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(head)
            first = sock.recv(65536)
            assert b"100 Continue" in first
            sock.sendall(body)
            data = first
            while b"HTTP/1.1 200" not in data:
                data += sock.recv(65536)
            sock.close()
        finally:
            server.shutdown()

    def test_connection_close_honored(self):
        server = self._serve()
        try:
            body = nn_body(["n1"])
            head = (
                f"POST /scheduler/prioritize HTTP/1.1\r\n"
                f"Host: x\r\nContent-Type: application/json\r\n"
                f"Connection: close\r\n"
                f"Content-Length: {len(body)}\r\n\r\n"
            ).encode()
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(head + body)
            data = b""
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                data += chunk
            assert b"HTTP/1.1 200" in data
            assert b"Connection: close" in data
            sock.close()
        finally:
            server.shutdown()

    def test_bad_request_line(self):
        server = self._serve()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(b"GARBAGE\r\n\r\n")
            data = sock.recv(65536)
            assert b"400" in data
            sock.close()
        finally:
            server.shutdown()

    def test_bad_content_length(self):
        server = self._serve()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(
                b"POST /scheduler/prioritize HTTP/1.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: nope\r\n\r\n"
            )
            data = sock.recv(65536)
            assert b"400" in data
            sock.close()
        finally:
            server.shutdown()

    def test_negative_content_length_rejected(self):
        server = self._serve()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(
                b"POST /scheduler/prioritize HTTP/1.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: -1\r\n\r\n"
            )
            data = sock.recv(65536)
            assert b"400" in data
            sock.close()
        finally:
            server.shutdown()

    def test_lenient_content_length_forms_rejected(self):
        # int() would accept these; strict ASCII-digit framing must not
        server = self._serve()
        try:
            # note " 7" is absent: OWS around header values is stripped at
            # parse time (legal per RFC 7230), leaving plain digits
            for bad in (b"+5", b"5_0", b"0x10"):
                sock = socket.create_connection(("127.0.0.1", server.port))
                sock.sendall(
                    b"POST /scheduler/prioritize HTTP/1.1\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + bad + b"\r\n\r\n"
                )
                data = sock.recv(65536)
                assert b"400" in data, bad
                sock.close()
        finally:
            server.shutdown()

    def test_header_name_trailing_whitespace_rejected(self):
        # 'Transfer-Encoding : chunked' must not dodge the TE check
        server = self._serve()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(
                b"POST /scheduler/prioritize HTTP/1.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Transfer-Encoding : chunked\r\n\r\n"
            )
            data = sock.recv(65536)
            assert b"400" in data
            sock.close()
        finally:
            server.shutdown()

    def test_conflicting_duplicate_content_length_rejected(self):
        server = self._serve()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(
                b"POST /scheduler/prioritize HTTP/1.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: 10\r\n"
                b"Content-Length: 0\r\n\r\n"
            )
            data = sock.recv(65536)
            assert b"400" in data
            sock.close()
        finally:
            server.shutdown()

    def test_transfer_encoding_rejected(self):
        server = self._serve()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(
                b"POST /scheduler/prioritize HTTP/1.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n"
                b"5\r\nhello\r\n0\r\n\r\n"
            )
            data = b""
            while True:
                chunk = sock.recv(65536)
                if not chunk:
                    break
                data += chunk
            assert b"400" in data
            assert b"Connection: close" in data
            sock.close()
        finally:
            server.shutdown()

    def test_unbounded_header_stream_rejected(self):
        server = self._serve()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            sock.sendall(b"POST /scheduler/prioritize HTTP/1.1\r\n")
            filler = b"X-Pad: " + b"a" * 8000 + b"\r\n"
            data = b""
            # interleave sends with short reads: once the server answers
            # 431 we stop sending, so it never closes with unread bytes
            # in its buffer (close-with-pending-data would RST and could
            # discard the buffered response)
            for _ in range(12):  # ~96 KB of header bytes, no blank line
                try:
                    sock.sendall(filler)
                except OSError:
                    break
                sock.settimeout(0.2)
                try:
                    chunk = sock.recv(65536)
                    if chunk:
                        data += chunk
                        break
                except TimeoutError:
                    continue
                except OSError:
                    break
            sock.settimeout(5.0)
            try:
                while b"\r\n\r\n" not in data:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    data += chunk
            except OSError:
                pass
            assert b"431" in data
            sock.close()
        finally:
            server.shutdown()

    def test_handler_exception_returns_500(self):
        class Boom:
            def prioritize(self, request):
                raise RuntimeError("boom")

            filter = bind = prioritize

        server = Server(Boom())
        server.start_server(port="0", unsafe=True, host="127.0.0.1", block=False)
        server.wait_ready()
        try:
            import http.client

            conn = http.client.HTTPConnection("127.0.0.1", server.port)
            conn.request(
                "POST", "/scheduler/prioritize", body=b"{}",
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            assert resp.status == 500
            resp.read()
            conn.close()
        finally:
            server.shutdown()

"""Fault-tolerant control plane (docs/robustness.md): retry/backoff
schedules, circuit transitions, degraded modes, and the end-to-end chaos
invariant — all deterministic: fault plans + fake clocks, zero real
sleeps, zero wall-clock randomness."""

import json
import threading

import pytest

from benchmarks.chaos_load import ChaosScenario
from platform_aware_scheduling_tpu.kube.client import (
    ConflictError,
    KubeError,
    NotFoundError,
)
from platform_aware_scheduling_tpu.kube.retry import (
    STATE_CLOSED,
    STATE_HALF_OPEN,
    STATE_OPEN,
    CircuitBreaker,
    CircuitBreakerRegistry,
    CircuitOpenError,
    FaultTolerantClient,
    RetryPolicy,
    backoff_delay,
)
from platform_aware_scheduling_tpu.tas.degraded import (
    ACTION_FAIL_CLOSED,
    ACTION_FAIL_OPEN,
    ACTION_LAST_KNOWN_GOOD,
    ACTION_NEUTRAL,
    ACTION_NORMAL,
    DegradedModeController,
)
from platform_aware_scheduling_tpu.testing.builders import make_node, make_pod
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.testing.faults import (
    FakeClock,
    FakeMetricsClient,
    FaultPlan,
    FaultyClient,
)
from platform_aware_scheduling_tpu.utils.tracing import CounterSet


# ---------------------------------------------------------------------------
# retry policy: deterministic schedules
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_backoff_deterministic_and_capped(self):
        policy = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, seed=3)
        a = [policy.backoff(n, verb="list_nodes") for n in range(1, 8)]
        b = [policy.backoff(n, verb="list_nodes") for n in range(1, 8)]
        assert a == b, "same seed+verb+attempt must give the same delay"
        # jittered exponential: within [0.5, 1.0) of the raw schedule
        for n, delay in enumerate(a, 1):
            raw = min(1.0, 0.1 * 2 ** (n - 1))
            assert raw * 0.5 <= delay < raw
        # distinct verbs get distinct (but still deterministic) schedules
        assert a != [policy.backoff(n, verb="get_pod") for n in range(1, 8)]

    def test_retry_after_is_a_floor(self):
        policy = RetryPolicy(base_delay_s=0.1, max_delay_s=1.0)
        assert policy.backoff(1, verb="v", retry_after_s=7.5) == 7.5
        # a tiny Retry-After never shrinks the computed backoff
        computed = policy.backoff(5, verb="v")
        assert policy.backoff(5, verb="v", retry_after_s=0.001) == computed

    def test_backoff_delay_seed_independent_of_process(self):
        # pinned values: stable_hash + LCG are process-independent, so
        # these exact numbers hold on every run and every machine
        assert backoff_delay(1, 1.0, 10.0, seed=0) == backoff_delay(
            1, 1.0, 10.0, seed=0
        )
        assert backoff_delay(1, 1.0, 10.0, seed=0) != backoff_delay(
            1, 1.0, 10.0, seed=1
        )


class TestRetryingReads:
    def _client(self, plan, clock, **kw):
        fake = FakeKubeClient()
        fake.add_node(make_node("n1"))
        fake.fault_plan = plan
        fake.fault_clock = clock
        sleeps = []

        def sleep(s):
            sleeps.append(s)
            clock.advance(s)

        ft = FaultTolerantClient(
            fake,
            policy=kw.pop("policy", RetryPolicy(
                max_attempts=4, base_delay_s=0.1, max_delay_s=1.0,
                deadline_s=30.0,
            )),
            breakers=CircuitBreakerRegistry(
                failure_threshold=kw.pop("threshold", 100),
                reset_timeout_s=5.0,
                clock=clock.now,
            ),
            clock=clock.now,
            sleep=sleep,
            counters=kw.pop("counters", CounterSet()),
        )
        return fake, ft, sleeps

    def test_read_retries_through_transient_errors(self):
        clock = FakeClock()
        plan = FaultPlan().fail("list_nodes", 3, status=503)
        counters = CounterSet()
        fake, ft, sleeps = self._client(plan, clock, counters=counters)
        nodes = ft.list_nodes()
        assert [n.name for n in nodes] == ["n1"]
        assert plan.call_count("list_nodes") == 4  # 3 failures + success
        assert len(sleeps) == 3  # one backoff per retry, nonzero
        assert all(s > 0 for s in sleeps)
        assert sleeps == sorted(sleeps)  # monotone under the cap
        assert counters.get(
            "pas_kube_retry_total",
            labels={"verb": "list_nodes", "reason": "server_error"},
        ) == 3

    def test_exhausted_retries_give_up_with_counter(self):
        clock = FakeClock()
        plan = FaultPlan().outage("list_nodes", status=503)
        counters = CounterSet()
        fake, ft, sleeps = self._client(plan, clock, counters=counters)
        with pytest.raises(KubeError):
            ft.list_nodes()
        assert plan.call_count("list_nodes") == 4  # max_attempts, bounded
        assert counters.get(
            "pas_kube_giveup_total", labels={"verb": "list_nodes"}
        ) == 1

    def test_empty_metric_answer_is_deterministic_not_a_circuit_failure(self):
        """A healthy metrics API answering 'no metric found' must not be
        retried and must not count against the metrics circuit — a
        missing metric opening the circuit would force degraded mode on
        a perfectly healthy cluster."""
        from platform_aware_scheduling_tpu.tas.metrics import MetricsError

        clock = FakeClock()
        metrics = FakeMetricsClient()  # empty store: every fetch 'not found'
        breakers = CircuitBreakerRegistry(
            failure_threshold=2, reset_timeout_s=5.0, clock=clock.now
        )
        ft = FaultTolerantClient(
            metrics, breakers=breakers, clock=clock.now, sleep=clock.sleep,
            counters=CounterSet(),
        )
        for _ in range(6):
            with pytest.raises(MetricsError):
                ft.get_node_metric("ghost")
        assert breakers.states().get("metrics", STATE_CLOSED) == STATE_CLOSED
        # but a WRAPPED transport failure (MetricsError from KubeError)
        # still classifies as retryable through its __cause__
        from platform_aware_scheduling_tpu.kube.retry import retry_reason

        try:
            try:
                raise KubeError("boom", status=503)
            except KubeError as inner:
                raise MetricsError("unable to fetch metrics") from inner
        except MetricsError as outer:
            assert retry_reason(outer) == "server_error"
        assert retry_reason(MetricsError("no metric ghost found")) is None

    def test_not_found_is_never_retried(self):
        clock = FakeClock()
        fake, ft, sleeps = self._client(FaultPlan(), clock)
        with pytest.raises(NotFoundError):
            ft.get_node("missing")
        assert sleeps == []

    def test_retry_after_header_honored(self):
        clock = FakeClock()
        plan = FaultPlan().fail(
            "list_nodes", 1,
            exc_factory=lambda: KubeError(
                "throttled", status=429, retry_after=9.0
            ),
        )
        fake, ft, sleeps = self._client(plan, clock)
        ft.list_nodes()
        assert sleeps == [9.0]

    def test_deadline_stops_retrying_early(self):
        clock = FakeClock()
        plan = FaultPlan().outage("list_nodes", status=503)
        fake, ft, sleeps = self._client(
            plan, clock,
            policy=RetryPolicy(
                max_attempts=10, base_delay_s=2.0, max_delay_s=2.0,
                deadline_s=3.0,
            ),
        )
        with pytest.raises(KubeError):
            ft.list_nodes()
        # the first backoff (~1-2 s) fits the 3 s deadline, the next
        # would overshoot -> bounded attempts, no 10-try storm
        assert plan.call_count("list_nodes") <= 3


class TestWritesNeverBlindRetry:
    def test_write_failure_single_attempt(self):
        clock = FakeClock()
        fake = FakeKubeClient()
        fake.add_node(make_node("n1"))
        plan = FaultPlan().fail("patch_node", 1, status=503)
        fake.fault_plan = plan
        fake.fault_clock = clock
        ft = FaultTolerantClient(
            fake, clock=clock.now, sleep=clock.sleep,
            counters=CounterSet(),
        )
        with pytest.raises(KubeError):
            ft.patch_node("n1", [{"op": "add", "path": "/metadata/labels/x",
                                  "value": "y"}])
        assert plan.call_count("patch_node") == 1  # ambiguous: NO retry
        # the next call goes straight through (plan exhausted)
        ft.patch_node("n1", [{"op": "add", "path": "/metadata/labels/x",
                              "value": "y"}])
        assert plan.call_count("patch_node") == 2

    def test_conflict_passes_through_unwrapped(self):
        fake = FakeKubeClient()
        fake.add_pod(make_pod("p1"))
        fake.update_pod_conflicts_remaining = 1
        ft = FaultTolerantClient(fake, counters=CounterSet())
        with pytest.raises(ConflictError):
            ft.update_pod(fake.get_pod("default", "p1"))


# ---------------------------------------------------------------------------
# circuit breaker
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def test_open_half_open_close_cycle(self):
        clock = FakeClock()
        counters = CounterSet()
        cb = CircuitBreaker(
            "kube", failure_threshold=3, reset_timeout_s=10.0,
            clock=clock.now, counters=counters,
        )
        assert cb.state == STATE_CLOSED
        for _ in range(3):
            assert cb.allow()
            cb.record_failure()
        assert cb.state == STATE_OPEN
        assert not cb.allow()  # fail-fast while open
        clock.advance(10.0)
        assert cb.state == STATE_HALF_OPEN
        assert cb.allow()       # the single probe
        assert not cb.allow()   # second caller refused while probing
        cb.record_success()
        assert cb.state == STATE_CLOSED
        # gauge + transition counters moved
        assert counters.get(
            "pas_circuit_state", kind="gauge", labels={"group": "kube"}
        ) == 0
        assert counters.get(
            "pas_circuit_transitions_total",
            labels={"group": "kube", "to": STATE_OPEN},
        ) == 1
        assert counters.get(
            "pas_circuit_transitions_total",
            labels={"group": "kube", "to": STATE_CLOSED},
        ) == 1

    def test_failed_probe_reopens_and_rearms_timer(self):
        clock = FakeClock()
        cb = CircuitBreaker(
            "kube", failure_threshold=1, reset_timeout_s=10.0,
            clock=clock.now, counters=CounterSet(),
        )
        cb.record_failure()
        assert cb.state == STATE_OPEN
        clock.advance(10.0)
        assert cb.allow()
        cb.record_failure()  # probe failed
        assert cb.state == STATE_OPEN
        clock.advance(5.0)
        assert not cb.allow()  # timer re-armed: 5 s < 10 s
        clock.advance(5.0)
        assert cb.allow()

    def test_success_resets_consecutive_count(self):
        clock = FakeClock()
        cb = CircuitBreaker(
            "kube", failure_threshold=3, clock=clock.now,
            counters=CounterSet(),
        )
        cb.record_failure()
        cb.record_failure()
        cb.record_success()  # N must be CONSECUTIVE
        cb.record_failure()
        cb.record_failure()
        assert cb.state == STATE_CLOSED

    def test_open_circuit_fails_fast_without_touching_inner(self):
        clock = FakeClock()
        fake = FakeKubeClient()
        fake.add_node(make_node("n1"))
        plan = FaultPlan().outage("list_nodes")
        fake.fault_plan = plan
        fake.fault_clock = clock
        ft = FaultTolerantClient(
            fake,
            policy=RetryPolicy(max_attempts=2, base_delay_s=0.01,
                               max_delay_s=0.01),
            breakers=CircuitBreakerRegistry(
                failure_threshold=2, reset_timeout_s=60.0, clock=clock.now
            ),
            clock=clock.now, sleep=clock.sleep, counters=CounterSet(),
        )
        with pytest.raises(KubeError):
            ft.list_nodes()  # 2 attempts -> circuit opens
        calls_after_open = plan.call_count("list_nodes")
        for _ in range(5):
            with pytest.raises(CircuitOpenError):
                ft.list_nodes()
        assert plan.call_count("list_nodes") == calls_after_open

    def test_write_refused_while_open(self):
        clock = FakeClock()
        fake = FakeKubeClient()
        fake.add_pod(make_pod("p1", node_name="n1", phase="Running"))
        breakers = CircuitBreakerRegistry(
            failure_threshold=1, reset_timeout_s=60.0, clock=clock.now
        )
        breakers.breaker("kube").record_failure()  # open it
        ft = FaultTolerantClient(
            fake, breakers=breakers, clock=clock.now, sleep=clock.sleep,
            counters=CounterSet(),
        )
        with pytest.raises(CircuitOpenError):
            ft.evict_pod("default", "p1")
        assert fake.evictions == []


# ---------------------------------------------------------------------------
# fault plan determinism
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_error_rate_is_seed_deterministic(self):
        def fire_pattern(seed):
            plan = FaultPlan(seed=seed).error_rate("v", 0.3)
            return [plan.next("v") is not None for _ in range(50)]

        assert fire_pattern(1) == fire_pattern(1)
        assert fire_pattern(1) != fire_pattern(2)
        rate = sum(fire_pattern(1)) / 50
        assert 0.1 < rate < 0.5  # roughly the asked-for rate

    def test_flap_schedule(self):
        plan = FaultPlan().flap("v", ok=2, fail=1, cycles=2)
        outcomes = [plan.next("v") is None for _ in range(6)]
        assert outcomes == [True, True, False, True, True, False]

    def test_latency_advances_fault_clock_only(self):
        clock = FakeClock(start=100.0)
        plan = FaultPlan().latency("v", 1, 2.5)
        plan.apply("v", clock)
        assert clock.now() == 102.5

    def test_faulty_client_wrapper_intercepts_by_name(self):
        fake = FakeKubeClient()
        fake.add_node(make_node("n1"))
        plan = FaultPlan().fail("list_nodes", 1)
        wrapped = FaultyClient(fake, plan)
        with pytest.raises(KubeError):
            wrapped.list_nodes()
        assert len(wrapped.list_nodes()) == 1


# ---------------------------------------------------------------------------
# degraded modes
# ---------------------------------------------------------------------------


def _stale_cache(clock, period=1.0, metric="m", age=100.0):
    from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
    from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
    from platform_aware_scheduling_tpu.utils.quantity import Quantity

    cache = AutoUpdatingCache(counters=CounterSet(), clock=clock.now)
    cache._refresh_period = period
    cache.write_metric(metric, {"n1": NodeMetric(value=Quantity("1"))})
    cache.write_metric(metric)  # register
    cache.update_all_metrics(FakeMetricsClient({
        metric: {"n1": NodeMetric(value=Quantity("1"))}
    }))
    clock.advance(age)
    return cache


class TestDegradedModeController:
    def test_fresh_cache_is_normal(self):
        clock = FakeClock()
        cache = _stale_cache(clock, age=0.5)
        ctl = DegradedModeController(cache, counters=CounterSet())
        assert ctl.filter_decision()[0] == ACTION_NORMAL
        assert ctl.prioritize_decision()[0] == ACTION_NORMAL
        assert ctl.evictions_allowed()[0]

    def test_last_known_good_window_then_neutral(self):
        clock = FakeClock()
        # period 1 -> freshness bound 3 s, LKG bound 9 s.  Age 5: stale
        # but within LKG
        cache = _stale_cache(clock, age=5.0)
        ctl = DegradedModeController(cache, counters=CounterSet())
        assert ctl.filter_decision()[0] == ACTION_LAST_KNOWN_GOOD
        assert ctl.prioritize_decision()[0] == ACTION_LAST_KNOWN_GOOD
        assert not ctl.evictions_allowed()[0]  # suspended EVEN within LKG
        clock.advance(10.0)  # age 15: past the LKG bound
        assert ctl.filter_decision()[0] == ACTION_FAIL_OPEN
        assert ctl.prioritize_decision()[0] == ACTION_NEUTRAL

    def test_fail_open_vs_fail_closed_flag(self):
        clock = FakeClock()
        cache = _stale_cache(clock, age=100.0)
        open_ctl = DegradedModeController(
            cache, mode="fail-open", counters=CounterSet()
        )
        closed_ctl = DegradedModeController(
            cache, mode="fail-closed", counters=CounterSet()
        )
        assert open_ctl.filter_decision()[0] == ACTION_FAIL_OPEN
        assert closed_ctl.filter_decision()[0] == ACTION_FAIL_CLOSED

    def test_kube_circuit_open_suspends_evictions_only(self):
        clock = FakeClock()
        cache = _stale_cache(clock, age=0.1)  # telemetry fresh
        breakers = CircuitBreakerRegistry(
            failure_threshold=1, clock=clock.now
        )
        breakers.breaker("kube").record_failure()
        ctl = DegradedModeController(
            cache, breakers=breakers, counters=CounterSet()
        )
        assert ctl.filter_decision()[0] == ACTION_NORMAL  # telemetry fine
        allowed, reason = ctl.evictions_allowed()
        assert not allowed and "kube" in reason

    def test_degraded_gauges_published(self):
        clock = FakeClock()
        cache = _stale_cache(clock, age=100.0)
        counters = CounterSet()
        ctl = DegradedModeController(cache, counters=counters)
        ctl.evictions_allowed()
        assert counters.get(
            "pas_degraded", kind="gauge", labels={"subsystem": "telemetry"}
        ) == 1
        assert counters.get(
            "pas_degraded", kind="gauge", labels={"subsystem": "evictions"}
        ) == 1
        assert counters.get(
            "pas_degraded", kind="gauge", labels={"subsystem": "kube_api"}
        ) == 0


class TestDegradedFilterWire:
    """fail-open passes every candidate; fail-closed fails every
    candidate — through the real Filter verb, both wire modes."""

    def _scenario(self, mode):
        s = ChaosScenario(degraded_mode=mode, hysteresis_cycles=100)
        for _ in range(2):
            s.tick()  # healthy: telemetry lands
        s.plan.outage("get_node_metric")
        for _ in range(12):
            s.tick()  # well past freshness AND the LKG window
        return s

    def _filter(self, s, nodes_mode):
        from platform_aware_scheduling_tpu.extender.server import HTTPRequest

        names = [f"node-{i}" for i in range(s.num_nodes)]
        pod = {"metadata": {"name": "p", "namespace": "default",
                            "labels": {"telemetry-policy": "chaos-pol"}}}
        if nodes_mode == "nodenames":
            obj = {"Pod": pod, "NodeNames": names}
        else:
            obj = {"Pod": pod,
                   "Nodes": {"items": [{"metadata": {"name": n}}
                                       for n in names]}}
        request = HTTPRequest(
            "POST", "/scheduler/filter",
            {"Content-Type": "application/json"},
            json.dumps(obj).encode(),
        )
        response = s.extender.filter(request)
        assert response.status == 200
        return json.loads(response.body), names

    @pytest.mark.parametrize("nodes_mode", ["nodes", "nodenames"])
    def test_fail_open_passes_all(self, nodes_mode):
        s = self._scenario("fail-open")
        result, names = self._filter(s, nodes_mode)
        assert not result.get("FailedNodes")
        got = result.get("NodeNames") or []
        assert [n for n in got if n] == names

    @pytest.mark.parametrize("nodes_mode", ["nodes", "nodenames"])
    def test_fail_closed_fails_all(self, nodes_mode):
        s = self._scenario("fail-closed")
        result, names = self._filter(s, nodes_mode)
        assert set(result.get("FailedNodes") or {}) == set(names)
        assert [n for n in (result.get("NodeNames") or []) if n] == []

    def test_prioritize_neutral_when_past_lkg(self):
        from platform_aware_scheduling_tpu.extender.server import HTTPRequest

        s = self._scenario("last-known-good")
        names = [f"node-{i}" for i in range(s.num_nodes)]
        obj = {"Pod": {"metadata": {"name": "p", "namespace": "default",
                                    "labels": {"telemetry-policy":
                                               "chaos-pol"}}},
               "NodeNames": names}
        response = s.extender.prioritize(HTTPRequest(
            "POST", "/scheduler/prioritize",
            {"Content-Type": "application/json"}, json.dumps(obj).encode(),
        ))
        assert response.status == 200
        scores = json.loads(response.body)
        assert {e["Host"] for e in scores} == set(names)
        assert len({e["Score"] for e in scores}) == 1  # neutral: all equal


# ---------------------------------------------------------------------------
# the chaos invariant, end to end
# ---------------------------------------------------------------------------


class TestChaosInvariant:
    def test_outage_degrade_recover_resume(self):
        """ISSUE 5 acceptance: under a scripted 100% metrics outage the
        assembled service keeps serving (degraded, /readyz lists the
        reason), performs ZERO evictions, issues a bounded number of
        retries, and returns to ready within a bounded number of cycles
        after the fault clears."""
        s = ChaosScenario(hysteresis_cycles=3)
        # one healthy tick: telemetry lands, node-0 violates (streak 1 of
        # 3 -> no evictions yet)
        record = s.tick()
        assert record.get("violating_nodes") == ["node-0"]
        assert s.evictions() == 0
        assert s.ready()[0]

        # -- outage: metrics API 100% down ------------------------------
        s.plan.outage("get_node_metric", status=503)
        calls_before = s.plan.call_count("get_node_metric")
        for _ in range(10):
            s.tick()
        # zero evictions despite the standing violation in the stale data
        assert s.evictions() == 0
        # the service reports WHY on /readyz
        ready, conditions = s.ready()
        assert not ready
        by_name = {c["name"]: c for c in conditions}
        assert not by_name["telemetry_fresh"]["ok"]
        assert not by_name["degraded_mode"]["ok"]
        assert "degraded" in by_name["degraded_mode"]["reason"]
        # bounded retries: the circuit caps the storm well below
        # ticks x max_attempts
        calls_during = s.plan.call_count("get_node_metric") - calls_before
        assert calls_during <= 10 * s.retry_policy.max_attempts
        assert calls_during < 15, f"retry storm: {calls_during} calls"
        assert s.breakers.states()["metrics"] != STATE_CLOSED
        # the rebalancer shows the suspension on its status JSON
        status = s.rebalancer.status()
        assert status["evictions_suspended"]
        assert status["degraded"]["evictions"]["allowed"] is False
        assert status["last_plan"].get("suspended")

        # -- recover ----------------------------------------------------
        s.plan.clear("get_node_metric")
        recovered_at = None
        for cycle in range(6):
            s.tick()
            if s.ready()[0]:
                recovered_at = cycle
                break
        assert recovered_at is not None, "never returned to ready"
        assert s.breakers.states()["metrics"] == STATE_CLOSED

        # -- resume: the standing violation now drives real evictions ---
        for _ in range(4):
            s.tick()
        assert s.evictions() > 0, "evictions must resume after recovery"

    def test_dry_run_stays_dry_through_chaos(self):
        s = ChaosScenario(rebalance_mode="dry-run", hysteresis_cycles=1)
        for _ in range(3):
            s.tick()
        s.plan.outage("get_node_metric")
        for _ in range(5):
            s.tick()
        s.plan.clear("get_node_metric")
        for _ in range(5):
            s.tick()
        assert s.evictions() == 0

    def test_kube_outage_also_suspends_evictions(self):
        """The OTHER half of the invariant: fresh telemetry but an open
        kube circuit must suspend evictions too."""
        s = ChaosScenario(hysteresis_cycles=1)
        s.breakers.breaker("kube")._failures = 0
        # trip the kube circuit directly (threshold 3)
        for _ in range(3):
            s.breakers.breaker("kube").record_failure()
        assert s.breakers.states()["kube"] == STATE_OPEN
        for _ in range(4):
            s.tick()
        assert s.evictions() == 0
        allowed, reason = s.degraded.evictions_allowed()
        assert not allowed and "kube" in reason

    def test_suspended_cycles_probe_the_kube_circuit_back_closed(self):
        """Liveness: the suspension gate removes every other kube-group
        call, so the suspended cycle itself must drive the half-open
        probe — otherwise an open kube circuit never closes and
        enforcement stays suspended forever after the API recovers."""
        s = ChaosScenario(hysteresis_cycles=1)
        for _ in range(3):
            s.breakers.breaker("kube").record_failure()
        assert s.breakers.states()["kube"] == STATE_OPEN
        # reset_timeout_s=5.0, period 1.0: by the 6th tick the breaker
        # is probe-eligible; the suspended cycle's list_nodes probe (the
        # fake kube is healthy) must close it and enforcement resume
        for _ in range(8):
            s.tick()
        assert s.breakers.states()["kube"] == STATE_CLOSED
        assert s.degraded.evictions_allowed()[0]
        for _ in range(3):
            s.tick()
        assert s.evictions() > 0, "enforcement must resume after recovery"


class TestChaosFrontEnds:
    """Recovery to ready through real /readyz on BOTH front-ends."""

    def _drive(self, start_server):
        from wirehelpers import get_request

        s = ChaosScenario(hysteresis_cycles=100)
        s.tick()
        server = start_server(s.extender)
        try:
            status, _, body = get_request(server.port, "/readyz")
            assert status == 200, body
            # outage long enough to blow the freshness bound
            s.plan.outage("get_node_metric")
            for _ in range(8):
                s.tick()
            status, _, body = get_request(server.port, "/readyz")
            assert status == 503
            payload = json.loads(body)
            failing = {c["name"]: c["reason"] for c in payload["conditions"]
                       if not c["ok"]}
            assert "telemetry_fresh" in failing
            assert "degraded_mode" in failing
            # the service KEEPS SERVING the scheduling verbs meanwhile
            from wirehelpers import post_bytes, raw_request

            names = [f"node-{i}" for i in range(s.num_nodes)]
            obj = {"Pod": {"metadata": {"name": "p", "namespace": "default",
                                        "labels": {"telemetry-policy":
                                                   "chaos-pol"}}},
                   "NodeNames": names}
            vstatus, _, vbody = raw_request(
                server.port,
                post_bytes("/scheduler/prioritize",
                           json.dumps(obj).encode()),
            )
            assert vstatus == 200
            assert json.loads(vbody), "degraded prioritize must answer"
            # recover: ready again within bounded cycles
            s.plan.clear("get_node_metric")
            for _ in range(6):
                s.tick()
                status, _, _ = get_request(server.port, "/readyz")
                if status == 200:
                    break
            assert status == 200
        finally:
            server.shutdown()

    def test_threaded_front_end(self):
        from wirehelpers import start_threaded

        self._drive(start_threaded)

    def test_async_front_end(self):
        from wirehelpers import start_async

        self._drive(start_async)


# ---------------------------------------------------------------------------
# service assembly wiring
# ---------------------------------------------------------------------------


class TestAssemblyWiring:
    def test_assemble_attaches_degraded_controller_everywhere(self):
        from platform_aware_scheduling_tpu.cmd.tas import assemble
        from platform_aware_scheduling_tpu.tas.metrics import (
            DummyMetricsClient,
        )

        fake = FakeKubeClient()
        breakers = CircuitBreakerRegistry(counters=CounterSet())
        pieces = assemble(
            fake,
            DummyMetricsClient({}),
            sync_period_s=3600.0,
            breakers=breakers,
            degraded_mode="fail-closed",
            rebalance_mode="dry-run",
        )
        cache, mirror, extender, controller, enforcer, stop = pieces
        try:
            assert extender.degraded is not None
            assert extender.degraded.mode == "fail-closed"
            assert enforcer.degraded is extender.degraded
            assert extender.rebalancer.degraded is extender.degraded
            assert extender.degraded.breakers is breakers
            names = [name for name, _ in extender.readiness_conditions()]
            assert "degraded_mode" in names
        finally:
            stop.set()

    def test_mains_accept_robustness_flags(self):
        from platform_aware_scheduling_tpu.cmd import gas, tas

        shared = [
            "--retryMaxAttempts", "7",
            "--retryBaseDelay", "50ms",
            "--circuitFailureThreshold", "9",
            "--circuitResetTimeout", "1m",
        ]
        args = tas.build_arg_parser().parse_args(
            shared + ["--degradedMode", "fail-open"]
        )
        assert args.retryMaxAttempts == 7
        assert args.degradedMode == "fail-open"
        gas_args = gas.build_arg_parser().parse_args(shared)
        assert gas_args.retryMaxAttempts == 7
        # GAS builds no DegradedModeController: the flag must not exist
        # there (a silently-ignored flag is an operator trap)
        assert not hasattr(gas_args, "degradedMode")
        with pytest.raises(SystemExit):
            gas.build_arg_parser().parse_args(
                shared + ["--degradedMode", "fail-open"]
            )
        from platform_aware_scheduling_tpu.cmd.common import (
            build_fault_tolerance,
        )

        policy, breakers = build_fault_tolerance(args)
        assert policy.max_attempts == 7
        assert policy.base_delay_s == pytest.approx(0.05)
        assert breakers.failure_threshold == 9
        assert breakers.reset_timeout_s == 60.0


# ---------------------------------------------------------------------------
# satellites: GAS conflict-retry backoff
# ---------------------------------------------------------------------------


class TestGASAnnotateBackoff:
    def test_conflict_retries_back_off_on_fake_clock(self):
        """The annotate conflict-retry loop must SLEEP between attempts
        (the reference hammered with zero delay) — attempt timestamps on
        a fake clock pin the deterministic backoff schedule."""
        from platform_aware_scheduling_tpu.gas.cache import Cache
        from platform_aware_scheduling_tpu.gas.scheduler import GASExtender

        clock = FakeClock()
        stamps = []
        kube = FakeKubeClient()
        kube.add_node(make_node(
            "n1",
            labels={"gpu.intel.com/cards": "card0"},
            allocatable={"gpu.intel.com/i915": "4",
                         "gpu.intel.com/millicores": "4000"},
        ))
        pod = make_pod("p", container_requests=[
            {"gpu.intel.com/i915": "1", "gpu.intel.com/millicores": "100"}])
        kube.add_pod(pod)
        original_update = kube.update_pod

        def stamping_update(p):
            stamps.append(clock.now())
            return original_update(p)

        kube.update_pod = stamping_update
        kube.update_pod_conflicts_remaining = 3
        cache = Cache(kube, start=False)
        ext = GASExtender(
            kube, cache=cache, use_device=False, sleep=clock.sleep,
        )
        cache.start()
        try:
            from platform_aware_scheduling_tpu.extender.server import (
                HTTPRequest,
            )

            body = json.dumps({
                "PodName": "p", "PodNamespace": "default",
                "PodUID": pod.uid, "Node": "n1",
            }).encode()
            response = ext.bind(HTTPRequest(
                "POST", "/scheduler/bind",
                {"Content-Type": "application/json"}, body,
            ))
            assert json.loads(response.body) == {"Error": ""}
        finally:
            cache.stop()
        assert len(stamps) == 4  # 3 conflicts + success
        gaps = [b - a for a, b in zip(stamps, stamps[1:])]
        assert all(g > 0 for g in gaps), f"zero-sleep retry storm: {gaps}"
        expected = [
            ext.retry_policy.backoff(n, verb="update_pod")
            for n in (1, 2, 3)
        ]
        assert gaps == pytest.approx(expected)


# ---------------------------------------------------------------------------
# the FaultPlan contract itself: resolution order + seeded determinism
# ---------------------------------------------------------------------------


class TestFaultPlanResolutionOrder:
    """Pins the docstring contract (testing/faults.py FaultPlan): per
    call, an outage wins; else the next scripted entry (verb before the
    ``"*"`` wildcard) is consumed; else the seeded error rate decides;
    exhausted scripts mean healthy.  The fuzzer's fault events lean on
    this order — an outage must mask, not consume, whatever else is
    scheduled for the verb."""

    def test_outage_wins_and_preserves_the_script(self):
        clock = FakeClock()
        plan = FaultPlan().latency("v", 2, 5.0).outage("v", status=503)
        t0 = clock.now()
        for _ in range(3):
            with pytest.raises(KubeError):
                plan.apply("v", clock)
        # the outage answered every call: the latency script was NOT
        # consumed and the fault clock never advanced
        assert clock.now() == t0
        with plan._lock:
            assert len(plan._scripts["v"]) == 2
        assert plan.call_count("v") == 3

    def test_script_beats_rate_then_rate_takes_over(self):
        clock = FakeClock()
        plan = FaultPlan(seed=5).latency("v", 2, 5.0).error_rate("v", 1.0)
        t0 = clock.now()
        plan.apply("v", clock)  # scripted latency: slow, not failing
        plan.apply("v", clock)
        assert clock.now() == t0 + 10.0
        with pytest.raises(KubeError):
            plan.apply("v", clock)  # script exhausted: the rate fires

    def test_verb_script_before_wildcard_then_healthy(self):
        clock = FakeClock()
        plan = FaultPlan().fail("*", 1).latency("v", 1, 1.0)
        t0 = clock.now()
        plan.apply("v", clock)  # the verb's own script first
        assert clock.now() == t0 + 1.0
        with pytest.raises(KubeError):
            plan.apply("v", clock)  # then the wildcard entry
        plan.apply("v", clock)  # everything exhausted: healthy
        assert plan.call_count("v") == 3


class TestErrorRateDeterminism:
    """error_rate is a pure function of (seed, verb, call index) —
    the property the fuzz engine's byte-identical-replay pin rides."""

    def _fire_indexes(self, seed, n=400, rate=0.3):
        plan = FaultPlan(seed=seed).error_rate("v", rate)
        return [i for i in range(n) if plan.next("v") is not None]

    def test_pure_function_of_seed_verb_and_index(self):
        a = self._fire_indexes(11)
        assert a == self._fire_indexes(11)
        assert a != self._fire_indexes(12)
        assert 0 < len(a) < 400  # a real rate, not all-or-nothing
        # distinct verbs draw distinct (deterministic) streams
        plan = FaultPlan(seed=11).error_rate("w", 0.3)
        b = [i for i in range(400) if plan.next("w") is not None]
        assert a != b

    def test_concurrent_callers_see_the_same_outcome_multiset(self):
        """Call-index allocation is atomic under the plan's lock, so
        whichever THREAD draws index n sees outcome f(seed, verb, n):
        the total count of fired faults is interleaving-independent
        and equal to the sequential run's."""
        expected = len(self._fire_indexes(11))
        for _round in range(2):  # two genuinely different interleavings
            plan = FaultPlan(seed=11).error_rate("v", 0.3)
            fired = []

            def worker():
                count = 0
                for _ in range(50):
                    if plan.next("v") is not None:
                        count += 1
                fired.append(count)

            threads = [
                threading.Thread(target=worker) for _ in range(8)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert plan.call_count("v") == 400
            assert sum(fired) == expected

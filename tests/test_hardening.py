"""Hardening tier — what the reference's suite lacks (SURVEY §4 gaps):
mTLS handshake behavior over a live socket, concurrent bind/filter stress
on the GAS booking path, and the validation prestop runner."""

import json
import os
import ssl
import subprocess
import threading
import time
import urllib.error
import urllib.request

import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPResponse, Server
from platform_aware_scheduling_tpu.extender.types import FilterResult
from platform_aware_scheduling_tpu.gas.cache import Cache
from platform_aware_scheduling_tpu.gas.scheduler import GASExtender
from platform_aware_scheduling_tpu.testing.builders import make_node, make_pod
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient


class StubScheduler:
    def filter(self, request):
        return HTTPResponse.json(FilterResult(node_names=["n1"]).to_json())

    prioritize = filter

    def bind(self, request):
        return HTTPResponse(status=404)


def gen_certs(tmp_path):
    """Throwaway CA + server/client certs (SAN 127.0.0.1)."""
    ca_key = tmp_path / "ca.key"
    ca_crt = tmp_path / "ca.crt"
    run = lambda *cmd: subprocess.run(cmd, check=True, capture_output=True)
    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", str(ca_key), "-out", str(ca_crt), "-days", "1",
        "-subj", "/CN=test-ca")
    certs = {}
    for name in ("server", "client"):
        key = tmp_path / f"{name}.key"
        csr = tmp_path / f"{name}.csr"
        crt = tmp_path / f"{name}.crt"
        ext = tmp_path / f"{name}.ext"
        ext.write_text("subjectAltName=IP:127.0.0.1\n")
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", str(key), "-out", str(csr), "-subj", f"/CN={name}")
        run("openssl", "x509", "-req", "-in", str(csr), "-CA", str(ca_crt),
            "-CAkey", str(ca_key), "-CAcreateserial", "-out", str(crt),
            "-days", "1", "-extfile", str(ext))
        certs[name] = (str(crt), str(key))
    return str(ca_crt), certs


@pytest.fixture(scope="module")
def tls_server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("certs")
    ca, certs = gen_certs(tmp)
    server = Server(StubScheduler())
    thread = threading.Thread(
        target=lambda: server.start_server(
            port="0",
            cert_file=certs["server"][0],
            key_file=certs["server"][1],
            ca_file=ca,
            unsafe=False,
            host="127.0.0.1",
            block=True,
        ),
        daemon=True,
    )
    thread.start()
    assert server.wait_ready()
    yield server, ca, certs
    server.shutdown()


class TestMTLS:
    def _ctx(self, ca, client_cert=None):
        ctx = ssl.create_default_context(cafile=ca)
        ctx.check_hostname = False
        if client_cert:
            ctx.load_cert_chain(*client_cert)
        return ctx

    def _post(self, server, ctx):
        req = urllib.request.Request(
            f"https://127.0.0.1:{server.port}/scheduler/filter",
            data=b"{}",
            headers={"Content-Type": "application/json"},
        )
        return urllib.request.urlopen(req, timeout=5, context=ctx)

    def test_mutual_tls_roundtrip(self, tls_server):
        server, ca, certs = tls_server
        resp = self._post(server, self._ctx(ca, certs["client"]))
        assert resp.status == 200
        assert json.loads(resp.read())["NodeNames"] == ["n1"]

    def test_client_cert_required(self, tls_server):
        server, ca, _ = tls_server
        with pytest.raises((ssl.SSLError, urllib.error.URLError, ConnectionError)):
            self._post(server, self._ctx(ca))

    def test_tls12_minimum(self, tls_server):
        server, ca, certs = tls_server
        ctx = self._ctx(ca, certs["client"])
        ctx.minimum_version = ssl.TLSVersion.TLSv1_1
        ctx.maximum_version = ssl.TLSVersion.TLSv1_1
        with pytest.raises((ssl.SSLError, urllib.error.URLError, ConnectionError)):
            self._post(server, ctx)


class TestBindStress:
    """Concurrent binds + filters must keep booking consistent: every
    successful bind books exactly its request; total booked usage equals
    the sum over bound pods (the reference leaves this untested)."""

    def test_concurrent_bind_filter(self):
        kube = FakeKubeClient()
        kube.add_node(make_node(
            "n1",
            labels={"gpu.intel.com/cards": "card0.card1.card2.card3"},
            allocatable={"gpu.intel.com/i915": "16",
                         "gpu.intel.com/millicores": "4000"},
        ))
        pods = []
        for i in range(12):
            pod = make_pod(
                f"p{i}",
                container_requests=[{"gpu.intel.com/i915": "1",
                                     "gpu.intel.com/millicores": "250"}],
            )
            pods.append(pod)
            kube.add_pod(pod)
        cache = Cache(kube, start=False)
        ext = GASExtender(kube, cache=cache, use_device=False)
        cache.start()
        try:
            results = []
            lock = threading.Lock()

            def do_bind(pod):
                body = json.dumps({
                    "PodName": pod.name, "PodNamespace": "default",
                    "PodUID": pod.uid, "Node": "n1",
                }).encode()
                from platform_aware_scheduling_tpu.extender.server import HTTPRequest
                resp = ext.bind(HTTPRequest("POST", "/scheduler/bind",
                                            {"Content-Type": "application/json"},
                                            body))
                with lock:
                    results.append(json.loads(resp.body)["Error"])

            def do_filter():
                from platform_aware_scheduling_tpu.extender.server import HTTPRequest
                body = json.dumps({
                    "Pod": make_pod("probe", container_requests=[
                        {"gpu.intel.com/i915": "1",
                         "gpu.intel.com/millicores": "100"}]).raw,
                    "NodeNames": ["n1"],
                }).encode()
                ext.filter(HTTPRequest("POST", "/scheduler/filter",
                                       {"Content-Type": "application/json"},
                                       body))

            threads = [threading.Thread(target=do_bind, args=(p,)) for p in pods]
            threads += [threading.Thread(target=do_filter) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            bound = [e for e in results if e == ""]
            # 4000 millicores / 250 each = 16 would fit by millicores, but
            # 16 i915 / 4 cards = 4 per card x 4 cards = 16 i915 -> all 12 fit
            assert len(bound) == 12, results
            used = cache.get_node_resource_status("n1")
            total_milli = sum(rm.get("gpu.intel.com/millicores", 0)
                              for rm in used.values())
            total_i915 = sum(rm.get("gpu.intel.com/i915", 0)
                             for rm in used.values())
            assert total_milli == 12 * 250
            assert total_i915 == 12
            # per-card capacity never exceeded
            for card, rm in used.items():
                assert rm.get("gpu.intel.com/millicores", 0) <= 1000
                assert rm.get("gpu.intel.com/i915", 0) <= 4
        finally:
            cache.stop()


class TestValidationRunner:
    def test_prestop_triggers_event(self):
        from platform_aware_scheduling_tpu.testing.validation import serve_prestop

        trigger = threading.Event()
        server = serve_prestop(trigger, port=0)
        port = server.server_address[1]
        try:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/prestop", timeout=5
            )
            assert resp.status == 200
            assert trigger.wait(2)
        finally:
            server.shutdown()


class TestMetricsEndpoint:
    def test_latency_histograms_exported(self):
        import urllib.request
        from platform_aware_scheduling_tpu.tas.telemetryscheduler import (
            MetricsExtender,
        )
        from platform_aware_scheduling_tpu.testing.mocks import (
            mock_self_updating_cache,
        )

        ext = MetricsExtender(mock_self_updating_cache())
        server = Server(ext, metrics_provider=ext.recorder.prometheus_text)
        threading.Thread(
            target=lambda: server.start_server(
                port="0", unsafe=True, host="127.0.0.1", block=True
            ),
            daemon=True,
        ).start()
        assert server.wait_ready()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/scheduler/prioritize",
                data=b"{}",
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=5)
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=5
            )
            text = resp.read().decode()
            assert 'pas_request_duration_seconds_count{verb="prioritize"} 1' in text
            assert "pas_request_duration_seconds_bucket" in text
            # non-GET is rejected; absent provider stays 404 (parity default)
            post = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/metrics", data=b"x"
            )
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(post, timeout=5)
            assert err.value.code == 405
        finally:
            server.shutdown()

    def test_metrics_absent_without_provider(self):
        server = Server(StubScheduler())
        resp = server.route(
            __import__(
                "platform_aware_scheduling_tpu.extender.server",
                fromlist=["HTTPRequest"],
            ).HTTPRequest("GET", "/metrics", {}, b"")
        )
        assert resp.status == 404


class TestReferenceMockParity:
    def test_mock_caches_and_clients(self):
        from platform_aware_scheduling_tpu.tas.strategies.core import MetricEnforcer
        from platform_aware_scheduling_tpu.testing import mocks

        cache = mocks.mock_self_updating_cache()
        assert cache.read_metric("dummyMetric1")["node A"].value.cmp_int64(1) == 0
        client = mocks.dummy_metrics_client()
        assert "node B" in client.get_node_metric("dummyMetric2")
        enforcer = MetricEnforcer()
        strat = mocks.MockStrategy()
        enforcer.register_strategy_type(strat)
        enforcer.add_strategy(strat, strat.strategy_type())
        enforcer.enforce_strategy(strat.strategy_type(), cache)
        assert strat.enforce_calls == 1
        enforcer.remove_strategy(strat, strat.strategy_type())
        assert strat.cleanup_calls == 1

"""benchmarks/http_load.py harness correctness at tiny shapes.

The full-scale A/B runs in bench.py on real hardware; these tests pin the
harness itself: alias derivation from the actual sweep (the round-4 judge
hit a KeyError driving ``concurrency_sweep=(1,)``), the repeat-spread
field, and the >=100-request control sample.
"""

from benchmarks import http_load


class TestHttpLoadHarness:
    def test_run_c1_only_sweep(self):
        """A sweep without c=8 must work and omit the *_c8 aliases."""
        out = http_load.run(
            num_nodes=48,
            device_requests=8,
            control_requests=8,
            concurrency_sweep=(1,),
            warmup=2,
            repeats=1,
        )
        assert out["speedup_p99"] > 0
        assert "speedup_p99_miss" in out
        assert "speedup_p99_filter" in out
        assert "speedup_p99_c8" not in out
        assert "speedup_p99_filter_c8" not in out
        # hit-tier configs exist for both wire modes at c=1 only
        assert set(out["device"]) == set(out["control"])
        assert "prioritize_nodenames_c1" in out["device"]
        assert "prioritize_nodenames_c8" not in out["device"]

    def test_repeat_spread_surfaced(self):
        out = http_load.run(
            num_nodes=32,
            device_requests=6,
            control_requests=6,
            concurrency_sweep=(1,),
            warmup=1,
            repeats=2,
        )
        entry = out["device"]["prioritize_nodenames_c1"]
        assert len(entry["repeat_p99_ms"]) == 2
        # the reported p99 is the best (lowest) of the repeats
        assert entry["p99_ms"] == min(entry["repeat_p99_ms"])

    def test_filter_floor_breakdown_small(self):
        """The per-stage floor decomposition must produce every stage and
        internally-consistent magnitudes (stages <= the whole verb +
        slack) at tiny scale."""
        import pytest

        from platform_aware_scheduling_tpu.native import get_wirec

        if get_wirec() is None:
            pytest.skip("native scanner unavailable")
        out = http_load.filter_floor_breakdown(num_nodes=64, reps=5)
        for key in (
            "parse_us",
            "partition_encode_us",
            "verb_total_us",
            "nodes_hit_verb_us",
            "warm_parse_us",
            "warm_partition_encode_us",
            "warm_verb_total_us",
            "warm_prioritize_verb_us",
            "control_filter_ms",
            "http_floor_us",
        ):
            assert out[key] > 0, key
        # the verb includes parse + partition/encode (plus probe overhead)
        assert out["verb_total_us"] >= out["partition_encode_us"] * 0.5

    def test_serving_scaling_small(self):
        """The threaded-vs-async head-to-head harness end to end at tiny
        scale: both front-ends serve from their own subprocess and the
        scaling ratios are derived from the actual sweep."""
        out = http_load.serving_scaling(
            num_nodes=32,
            requests=8,
            warmup=2,
            repeats=1,
            concurrency_sweep=(1, 2),
        )
        for mode in ("threaded", "async"):
            assert out[mode]["c1"]["p99_ms"] > 0
            assert out[mode]["c2"]["p99_ms"] > 0
            assert out[mode]["p99_scaling_c2"] > 0
            assert out[mode]["rps_scaling_c2"] > 0

    def test_gas_load_small(self):
        """The GAS wire A/B harness end to end at tiny scale: both sides
        serve, speedups and the alias are produced."""
        from benchmarks import gas_load

        out = gas_load.run(
            num_nodes=24,
            device_requests=6,
            control_requests=6,
            concurrency_sweep=(1,),
            warmup=1,
            repeats=1,
        )
        assert out["speedup_p99_gas_filter"] > 0
        assert "gas_filter_c1" in out["device"]
        assert "gas_filter_c8" not in out["device"]

    def test_control_default_sample_size(self):
        """The control default must stay >=100 and divisible by the c=8
        sweep (so per-worker splits do not shrink the sample)."""
        import inspect

        sig = inspect.signature(http_load.run)
        default = sig.parameters["control_requests"].default
        assert default >= 100
        assert default % 8 == 0

"""Decision provenance (utils/decisions.py, ISSUE 6): reason-code parity
host↔device, concrete wire FailedNodes reasons identical on both
internal paths, ring bounds/eviction, /debug/decisions + /debug + the
/debug/traces filters on both front-ends, bind feedback closing records,
and the rebalance event linkage."""

import json

import pytest

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    Server,
)
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.strategies import dontschedule
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils import decisions, trace
from platform_aware_scheduling_tpu.utils.quantity import Quantity

from wirehelpers import post_bytes, raw_request, start_async


@pytest.fixture(autouse=True)
def fresh_log():
    """Each test gets a clean, enabled process-wide log and restores the
    default configuration afterwards."""
    decisions.DECISIONS.configure(enabled=True, capacity=512)
    yield decisions.DECISIONS
    decisions.DECISIONS.configure(enabled=True, capacity=512)


VALUES = {"n1": 100, "n2": 50, "n3": 10, "n4": 70}


def build(values=None, rules_spec=None, node_cache_capable=True):
    values = values or VALUES
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default",
        "pol",
        TASPolicy.from_obj(
            make_policy(
                "pol",
                strategies={
                    "scheduleonmetric": [rule("m", "GreaterThan", 0)],
                    "dontschedule": rules_spec
                    or [rule("m", "GreaterThan", 75)],
                },
            )
        ),
    )
    cache.write_metric(
        "m", {n: NodeMetric(value=Quantity(str(v))) for n, v in values.items()}
    )
    ext = MetricsExtender(
        cache, mirror=mirror, node_cache_capable=node_cache_capable
    )
    return cache, ext


def req(path, body, method="POST"):
    return HTTPRequest(
        method=method,
        path=path,
        headers={"Content-Type": "application/json"},
        body=body,
    )


def nn_body(names, pod="p", policy="pol"):
    meta = {"name": pod, "namespace": "default"}
    if policy:
        meta["labels"] = {"telemetry-policy": policy}
    return json.dumps({"Pod": {"metadata": meta}, "NodeNames": names}).encode()


def bind_body(pod="p", node="n2"):
    return json.dumps(
        {
            "PodName": pod,
            "PodNamespace": "default",
            "PodUID": "uid-1",
            "Node": node,
        }
    ).encode()


class TestReasonFormatting:
    def test_fmt_milli(self):
        assert decisions.fmt_milli(93000) == "93"
        assert decisions.fmt_milli(500) == "0.5"
        assert decisions.fmt_milli(-2500) == "-2.5"
        assert decisions.fmt_milli(0) == "0"
        assert decisions.fmt_milli(1001) == "1.001"
        assert decisions.fmt_milli(1100) == "1.1"

    def test_rule_reason_matches_issue_shape(self):
        assert (
            decisions.rule_reason("X", "cpu", "GreaterThan", "93", "80")
            == "policy X: metric cpu=93 > threshold 80"
        )
        assert "<" in decisions.rule_reason("X", "m", "LessThan", "1", "2")
        assert "==" in decisions.rule_reason("X", "m", "Equals", "1", "1")


class TestReasonCodeParity:
    """The tentpole invariant: the device kernel's rule-index vector,
    decoded host-side, must equal the host strategy's first-matching-rule
    recording — indexes AND strings, byte for byte."""

    def _device_reasons(self, ext):
        policy = ext.cache.read_policy("default", "pol")
        compiled, view = ext._device_policy(policy)
        explained = ext.fastpath.violation_reasons(compiled, view, "pol")
        assert explained is not None
        return explained

    def _host_reasons(self, ext):
        policy = ext.cache.read_policy("default", "pol")
        strategy = dontschedule.Strategy.from_policy_strategy(
            policy.strategies["dontschedule"]
        )
        return strategy.violated_details(ext.cache)

    def test_single_rule_parity(self):
        _, ext = build()
        _violations, dev_reasons, dev_indexes = self._device_reasons(ext)
        host = self._host_reasons(ext)
        assert dev_reasons == {n: d[1] for n, d in host.items()}
        assert dev_indexes == {n: d[0] for n, d in host.items()}
        assert dev_reasons == {
            "n1": "policy pol: metric m=100 > threshold 75"
        }

    def test_multi_rule_first_match_wins_identically(self):
        # n1=100 matches BOTH rules -> index 0 on both paths; n3=10
        # matches only rule 1
        _, ext = build(
            rules_spec=[
                rule("m", "GreaterThan", 75),
                rule("m", "LessThan", 20),
            ]
        )
        _violations, dev_reasons, dev_indexes = self._device_reasons(ext)
        host = self._host_reasons(ext)
        assert dev_indexes == {"n1": 0, "n3": 1}
        assert dev_indexes == {n: d[0] for n, d in host.items()}
        assert dev_reasons == {n: d[1] for n, d in host.items()}
        assert dev_reasons["n3"] == "policy pol: metric m=10 < threshold 20"

    def test_fractional_values_format_identically(self):
        _, ext = build(values={"n1": "1500m", "n2": "250m"}, rules_spec=[
            rule("m", "GreaterThan", 1),
        ])
        _v, dev_reasons, _i = self._device_reasons(ext)
        host = self._host_reasons(ext)
        assert dev_reasons == {n: d[1] for n, d in host.items()}
        assert dev_reasons == {
            "n1": "policy pol: metric m=1.5 > threshold 1"
        }


class TestWireReasons:
    """Satellite 1: every filtered node in a Filter response carries the
    concrete reason, identical on native and host paths."""

    def test_failed_nodes_values_native_vs_host(self, monkeypatch):
        _, ext = build()
        body = nn_body(["n1", "n2", "n3", "n4"])
        native = ext.filter(req("/scheduler/filter", body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.filter(req("/scheduler/filter", body))
        monkeypatch.delenv("PAS_TPU_NO_NATIVE")
        assert native.body == python.body
        out = json.loads(native.body)
        assert out["FailedNodes"] == {
            "n1": "policy pol: metric m=100 > threshold 75"
        }

    def test_nodes_mode_carries_reasons_too(self):
        _, ext = build()
        body = json.dumps(
            {
                "Pod": {
                    "metadata": {
                        "name": "p",
                        "namespace": "default",
                        "labels": {"telemetry-policy": "pol"},
                    }
                },
                "Nodes": {
                    "items": [
                        {"metadata": {"name": n}} for n in ("n1", "n2")
                    ]
                },
            }
        ).encode()
        out = json.loads(ext.filter(req("/scheduler/filter", body)).body)
        assert out["FailedNodes"] == {
            "n1": "policy pol: metric m=100 > threshold 75"
        }


class TestRecords:
    def test_filter_and_prioritize_record(self):
        _, ext = build()
        ext.prioritize(req("/scheduler/prioritize", nn_body(list(VALUES))))
        ext.filter(req("/scheduler/filter", nn_body(list(VALUES))))
        snap = decisions.DECISIONS.snapshot()
        assert snap["recorded_total"] == 2
        verbs = {r["verb"] for r in snap["records"]}
        assert verbs == {"prioritize", "filter"}
        fil = [r for r in snap["records"] if r["verb"] == "filter"][0]
        assert fil["pod"] == "default/p"
        assert fil["policy"] == "pol"
        assert fil["candidates"] == 4
        assert fil["filtered"] == 1
        assert fil["violating"] == {
            "n1": "policy pol: metric m=100 > threshold 75"
        }
        pri = [r for r in snap["records"] if r["verb"] == "prioritize"][0]
        assert pri["metric"] == "m"
        assert pri["operator"] == "GreaterThan"
        # score head: global ranking desc — n1(100) first
        assert pri["score_head"][0] == {"node": "n1", "score": 10}

    def test_cache_hit_still_records(self):
        _, ext = build()
        body = nn_body(list(VALUES))
        ext.filter(req("/scheduler/filter", body))
        ext.filter(req("/scheduler/filter", nn_body(list(VALUES), pod="q")))
        snap = decisions.DECISIONS.snapshot(verb="filter")
        assert snap["returned"] == 2
        paths = sorted(r["path"] for r in snap["records"])
        assert "cache_hit" in paths
        hit = [r for r in snap["records"] if r["path"] == "cache_hit"][0]
        assert hit["filtered"] == 1  # count rode the response-cache entry

    def test_disabled_log_records_nothing(self):
        decisions.DECISIONS.configure(enabled=False)
        _, ext = build()
        ext.filter(req("/scheduler/filter", nn_body(list(VALUES))))
        assert len(decisions.DECISIONS) == 0

    def test_ring_bounds_and_open_eviction(self):
        decisions.DECISIONS.configure(enabled=True, capacity=4)
        before = trace.COUNTERS.get("pas_decision_evicted_open_total")
        _, ext = build()
        for i in range(7):
            ext.filter(
                req("/scheduler/filter", nn_body(list(VALUES), pod=f"p{i}"))
            )
        assert len(decisions.DECISIONS) == 4
        snap = decisions.DECISIONS.snapshot(limit=100)
        assert snap["returned"] == 4
        assert snap["open"] == 4
        # three open records were overwritten before any feedback
        assert (
            trace.COUNTERS.get("pas_decision_evicted_open_total")
            == before + 3
        )

    def test_request_scope_violating_retention_bounded(self):
        """A fail-closed Filter at cluster scale must not pin a fresh
        full-size dict per ring slot: request-scope maps are truncated at
        retention time (shared policy_state maps stay full — one object
        per state)."""
        big = {f"n{i}": "degraded fail-closed" for i in range(1000)}
        decisions.DECISIONS.record_filter(
            pod_namespace="default",
            pod_name="big",
            policy="pol",
            path="fail_closed",
            candidates=1000,
            filtered=1000,
            violating=big,
            violating_scope="request",
            reason_code=decisions.CODE_FAIL_CLOSED,
        )
        shared = dict(big)
        decisions.DECISIONS.record_filter(
            pod_namespace="default",
            pod_name="shared",
            policy="pol",
            path="native",
            candidates=1000,
            filtered=1000,
            violating=shared,
            violating_scope="policy_state",
        )
        snap = decisions.DECISIONS.snapshot(pod="big")
        record = snap["records"][0]
        assert record["violating_truncated"] is True
        assert record["violating_total"] == 1000
        assert len(record["violating"]) == decisions.DETAIL_NODE_CAP
        raw = decisions.DECISIONS.snapshot(pod="shared")["records"][0]
        assert raw["violating_total"] == 1000
        # the shared map itself was NOT copied or truncated
        assert len(shared) == 1000

    def test_snapshot_filters(self):
        _, ext = build()
        for pod in ("a", "b"):
            ext.prioritize(
                req("/scheduler/prioritize", nn_body(list(VALUES), pod=pod))
            )
            ext.filter(
                req("/scheduler/filter", nn_body(list(VALUES), pod=pod))
            )
        snap = decisions.DECISIONS.snapshot(pod="a")
        assert {r["pod"] for r in snap["records"]} == {"default/a"}
        snap = decisions.DECISIONS.snapshot(verb="prioritize", limit=1)
        assert snap["returned"] == 1
        assert snap["records"][0]["verb"] == "prioritize"


class TestBindFeedback:
    def test_bind_closes_records_with_rank(self):
        _, ext = build()
        ext.prioritize(req("/scheduler/prioritize", nn_body(list(VALUES))))
        ext.filter(req("/scheduler/filter", nn_body(list(VALUES))))
        closed_before = trace.COUNTERS.get("pas_decision_closed_total")
        resp = ext.bind(req("/scheduler/bind", bind_body(node="n4")))
        assert resp.status == 404  # reference wire behavior untouched
        snap = decisions.DECISIONS.snapshot(pod="p")
        assert all(not r["open"] for r in snap["records"])
        pri = [r for r in snap["records"] if r["verb"] == "prioritize"][0]
        # ranking desc: n1(100) n4(70) n2(50) n3(10) -> n4 is rank 2
        assert pri["outcome"]["bound_node"] == "n4"
        assert pri["outcome"]["rank"] == 2
        assert (
            trace.COUNTERS.get("pas_decision_closed_total")
            == closed_before + 2
        )
        assert trace.COUNTERS.get(
            "pas_decision_chosen_rank_total", labels={"rank": "2"}
        ) >= 1
        assert decisions.DECISIONS.snapshot()["open"] == 0

    def test_bind_onto_violating_node_counts(self):
        _, ext = build()
        ext.filter(req("/scheduler/filter", nn_body(list(VALUES))))
        before = trace.COUNTERS.get("pas_decision_violated_at_bind_total")
        ext.bind(req("/scheduler/bind", bind_body(node="n1")))
        assert (
            trace.COUNTERS.get("pas_decision_violated_at_bind_total")
            == before + 1
        )
        record = decisions.DECISIONS.snapshot(pod="p")["records"][0]
        assert record["outcome"]["violated_at_bind"] is True
        assert "m=100" in record["outcome"]["violation_reason"]

    def test_bind_unknown_pod_is_noop(self):
        _, ext = build()
        resp = ext.bind(req("/scheduler/bind", bind_body(pod="ghost")))
        assert resp.status == 404


class TestRebalanceFeedback:
    def test_events_attach_to_open_records(self):
        log = decisions.DECISIONS
        log.record_filter(
            request_id="r1",
            pod_namespace="default",
            pod_name="mover",
            policy="pol",
            path="native",
            candidates=3,
            filtered=0,
        )
        log.observe_rebalance("default", "mover", "evicted", "n1 -> n2")
        record = log.snapshot(pod="mover")["records"][0]
        assert record["open"] is True  # eviction does not close; rebind will
        assert record["events"][0]["action"] == "evicted"
        assert record["events"][0]["detail"] == "n1 -> n2"

    def test_rebalance_cycle_record(self):
        log = decisions.DECISIONS
        log.record_rebalance({"cycle": 3, "mode": "active", "moves": []})
        snap = log.snapshot(verb="rebalance")
        record = snap["records"][0]
        assert record["detail"]["cycle"] == 3
        assert record["path"] == "active"
        # cycle summaries are born closed: nothing can ever feed them
        # back, so they must not inflate the open gauge or, on ring
        # eviction, the ring-too-small counter
        assert record["open"] is False
        assert snap["open"] == 0


class TestDebugEndpoints:
    """/debug/decisions 200/404/405 + query filtering, the /debug index,
    and the /debug/traces filters — threaded route (the async front-end
    routes these through the same Server.route; cross-socket coverage in
    TestFrontEndParity)."""

    def _server(self):
        _, ext = build()
        return ext, Server(ext, metrics_provider=ext.metrics_text)

    def test_decisions_endpoint_statuses(self):
        ext, server = self._server()
        resp = server.route(req("/debug/decisions", b"", method="GET"))
        assert resp.status == 200
        assert json.loads(resp.body)["enabled"] is True
        resp = server.route(req("/debug/decisions", b"", method="POST"))
        assert resp.status == 405
        decisions.DECISIONS.configure(enabled=False)
        resp = server.route(req("/debug/decisions", b"", method="GET"))
        assert resp.status == 404
        resp = server.route(
            req("/debug/decisions?limit=zap", b"", method="GET")
        )
        assert resp.status == 404  # disabled wins over bad params

    def test_decisions_query_filtering(self):
        ext, server = self._server()
        for pod in ("a", "b"):
            ext.prioritize(
                req("/scheduler/prioritize", nn_body(list(VALUES), pod=pod))
            )
            ext.filter(
                req("/scheduler/filter", nn_body(list(VALUES), pod=pod))
            )
        out = json.loads(
            server.route(
                req("/debug/decisions?pod=a&verb=filter", b"", method="GET")
            ).body
        )
        assert out["returned"] == 1
        assert out["records"][0]["pod"] == "default/a"
        assert out["records"][0]["verb"] == "filter"
        out = json.loads(
            server.route(
                req("/debug/decisions?limit=1", b"", method="GET")
            ).body
        )
        assert out["returned"] == 1
        # percent-encoded pod keys decode (standard clients encode '/')
        out = json.loads(
            server.route(
                req(
                    "/debug/decisions?pod=default%2Fa", b"", method="GET"
                )
            ).body
        )
        assert out["returned"] == 2
        assert {r["pod"] for r in out["records"]} == {"default/a"}
        resp = server.route(
            req("/debug/decisions?limit=zap", b"", method="GET")
        )
        assert resp.status == 400

    def test_debug_index(self):
        _, server = self._server()
        resp = server.route(req("/debug", b"", method="GET"))
        assert resp.status == 200
        paths = [e["path"] for e in json.loads(resp.body)["endpoints"]]
        for expected in (
            "/debug/traces",
            "/debug/decisions",
            "/debug/rebalance",
            "/debug/profile",
            "/healthz",
            "/readyz",
            "/metrics",
        ):
            assert expected in paths
        assert server.route(req("/debug", b"", method="POST")).status == 405

    def test_traces_filters(self):
        ext, server = self._server()
        ext.prioritize(req("/scheduler/prioritize", nn_body(list(VALUES))))
        ext.filter(req("/scheduler/filter", nn_body(list(VALUES))))
        # route()-driven verbs attach no spans; seed the ring directly
        for verb, ms in (("prioritize", 5.0), ("filter", 0.01)):
            span = trace.Span(f"POST /scheduler/{verb}")
            span.set("verb", verb)
            span.duration_s = ms / 1e3
            trace.TRACES.add(span)
        all_out = json.loads(
            server.route(req("/debug/traces", b"", method="GET")).body
        )
        out = json.loads(
            server.route(
                req("/debug/traces?verb=prioritize", b"", method="GET")
            ).body
        )
        assert out["verb"] == "prioritize"
        assert all(
            e["attrs"].get("verb") == "prioritize" for e in out["recent"]
        )
        out = json.loads(
            server.route(
                req("/debug/traces?min_ms=1", b"", method="GET")
            ).body
        )
        assert all(e["duration_ms"] >= 1 for e in out["recent"])
        assert len(all_out["recent"]) >= len(out["recent"])
        resp = server.route(
            req("/debug/traces?min_ms=zap", b"", method="GET")
        )
        assert resp.status == 400


@pytest.mark.skipif(get_wirec() is None, reason="no C toolchain")
class TestFrontEndParity:
    """Satellite 3: record parity threaded↔async over real sockets —
    the same request stream produces the same decision records through
    both front-ends."""

    FIELDS = (
        "verb",
        "pod",
        "policy",
        "candidates",
        "eligible",
        "filtered",
        "violating",
    )

    def _drive_threaded(self):
        _, ext = build()
        server = Server(ext, metrics_provider=ext.metrics_text)
        server.start_server(port="0", unsafe=True, host="127.0.0.1", block=False)
        try:
            assert server.wait_ready(10)
            return self._drive(server.port)
        finally:
            server.shutdown()

    def _drive_async(self):
        _, ext = build()
        server = start_async(ext)
        try:
            return self._drive(server.port)
        finally:
            server.shutdown()

    def _drive(self, port):
        for path in ("/scheduler/prioritize", "/scheduler/filter"):
            status, _, _ = raw_request(
                port, post_bytes(path, nn_body(list(VALUES)))
            )
            assert status == 200
        status, _, payload = raw_request(
            port,
            (
                b"GET /debug/decisions?limit=10 HTTP/1.1\r\nHost: t\r\n"
                b"Connection: close\r\n\r\n"
            ),
        )
        assert status == 200
        return json.loads(payload)

    def test_records_identical_across_front_ends(self):
        threaded = self._drive_threaded()
        decisions.DECISIONS.configure()  # reset between front-ends
        asynced = self._drive_async()
        assert threaded["recorded_total"] == asynced["recorded_total"] == 2

        def strip(records):
            return [
                {k: r.get(k) for k in self.FIELDS} for r in records
            ]

        assert strip(threaded["records"]) == strip(asynced["records"])
        # every record carries the (echoed) X-Request-ID of its request
        assert all(r["request_id"] for r in threaded["records"])
        assert all(r["request_id"] for r in asynced["records"])

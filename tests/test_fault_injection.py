"""Failure-path behavior (SURVEY §5.3): annotate conflict-retry, bind
rollback, watch-stream breakage recovery, metrics-client outages."""

import json
import time

import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.gas.cache import Cache, get_key
from platform_aware_scheduling_tpu.gas.scheduler import GASExtender
from platform_aware_scheduling_tpu.kube.client import KubeError
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.controller import TelemetryPolicyController
from platform_aware_scheduling_tpu.tas.strategies import core, dontschedule
from platform_aware_scheduling_tpu.testing.builders import (
    make_node,
    make_policy,
    make_pod,
    rule,
)
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient


def wait_until(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def post(obj) -> HTTPRequest:
    return HTTPRequest("POST", "/x", {"Content-Type": "application/json"},
                       json.dumps(obj).encode())


def gpu_setup():
    kube = FakeKubeClient()
    kube.add_node(make_node(
        "n1",
        labels={"gpu.intel.com/cards": "card0"},
        allocatable={"gpu.intel.com/i915": "4",
                     "gpu.intel.com/millicores": "4000"},
    ))
    pod = make_pod("p", container_requests=[
        {"gpu.intel.com/i915": "1", "gpu.intel.com/millicores": "100"}])
    kube.add_pod(pod)
    cache = Cache(kube, start=False)
    ext = GASExtender(kube, cache=cache, use_device=False)
    cache.start()
    return kube, cache, ext, pod


def bind_req(pod):
    return post({"PodName": pod.name, "PodNamespace": "default",
                 "PodUID": pod.uid, "Node": "n1"})


class TestAnnotateConflictRetry:
    def test_retries_through_conflicts(self):
        """4 conflicts < the 5-attempt retry budget -> bind succeeds
        (reference scheduler.go:90-110)."""
        kube, cache, ext, pod = gpu_setup()
        try:
            kube.update_pod_conflicts_remaining = 4
            resp = ext.bind(bind_req(pod))
            assert json.loads(resp.body) == {"Error": ""}
            assert kube.get_pod("default", "p").get_annotations()[
                "gas-container-cards"] == "card0"
        finally:
            cache.stop()

    def test_exhausted_retries_roll_back(self):
        kube, cache, ext, pod = gpu_setup()
        try:
            kube.update_pod_conflicts_remaining = 10
            resp = ext.bind(bind_req(pod))
            assert json.loads(resp.body)["Error"] != ""
            # booking rolled back, no binding recorded
            assert cache.get_node_resource_status("n1") in ({}, {"card0": {
                "gpu.intel.com/i915": 0, "gpu.intel.com/millicores": 0}})
            assert get_key(pod) not in cache.annotated_pods
            assert kube.bindings == []
        finally:
            cache.stop()


class TestBindAPIFailureRollback:
    def test_bind_subresource_failure_rolls_back(self):
        """Annotation succeeded but Bind API failed -> resources restored
        (reference scheduler.go:404-414)."""
        kube, cache, ext, pod = gpu_setup()
        try:
            kube.fail_next_bind = KubeError("apiserver unavailable", status=503)
            resp = ext.bind(bind_req(pod))
            assert "apiserver unavailable" in json.loads(resp.body)["Error"]
            status = cache.get_node_resource_status("n1")
            booked = sum(
                rm.get("gpu.intel.com/millicores", 0) for rm in status.values()
            )
            assert booked == 0
            assert get_key(pod) not in cache.annotated_pods
        finally:
            cache.stop()


class FlakyWatchClient:
    """Delegates to a FakeKubeClient but breaks the policy watch stream
    after each event (forcing the informer's relist path every time)."""

    def __init__(self, inner):
        self._inner = inner
        self.breaks = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def watch_taspolicies(self, namespace=None, **kw):
        iterator = self._inner.watch_taspolicies(namespace, **kw)

        def flaky():
            for event in iterator:
                yield event
                self.breaks += 1
                raise KubeError("watch stream reset", status=500)

        return flaky()


class TestWatchBreakRecovery:
    def test_controller_survives_watch_resets(self):
        kube = FakeKubeClient()
        flaky = FlakyWatchClient(kube)
        cache = AutoUpdatingCache()
        enforcer = core.MetricEnforcer(kube)
        enforcer.register_strategy_type(dontschedule.Strategy())
        controller = TelemetryPolicyController(flaky, cache, enforcer)
        informer = controller.run()
        assert informer.wait_for_cache_sync()
        try:
            kube.create_taspolicy(make_policy(
                "flaky-pol",
                strategies={"dontschedule": [rule("m", "LessThan", 1)]},
            ))
            assert wait_until(lambda: _has(cache, "default", "flaky-pol"))
            kube.delete_taspolicy("default", "flaky-pol")
            assert wait_until(lambda: not _has(cache, "default", "flaky-pol"))
        finally:
            informer.stop()


def _has(cache, ns, name):
    try:
        cache.read_policy(ns, name)
        return True
    except Exception:
        return False


class TestMetricsOutage:
    def test_periodic_update_survives_client_errors(self):
        """A failing metrics client must not kill the refresh loop or evict
        the last good values (autoupdating.go error path)."""
        from platform_aware_scheduling_tpu.tas.metrics import (
            DummyMetricsClient,
            MetricsError,
        )

        cache = AutoUpdatingCache()
        from platform_aware_scheduling_tpu.testing.mocks import (
            test_node_metric_custom_info,
        )

        good = test_node_metric_custom_info(["a"], [7])
        cache.write_metric("m", good)
        cache.write_metric("m")  # register for refresh

        class FlakyMetrics:
            def __init__(self):
                self.calls = 0

            def get_node_metric(self, name):
                self.calls += 1
                raise MetricsError("custom metrics api down")

        client = FlakyMetrics()
        stop = cache.start_periodic_update(0.02, client)
        try:
            assert wait_until(lambda: client.calls >= 3)
            # last good value still served
            assert cache.read_metric("m")["a"].value.cmp_int64(7) == 0
        finally:
            stop.set()

"""pascheck framework tests (platform_aware_scheduling_tpu/analysis/).

Each checker gets a seeded MUST-flag fixture — a minimal package tree
containing exactly the violation class the checker exists for — plus
pragma/baseline round-trips, CLI exit codes, and the repo gate: the
package as committed is pascheck-clean, and the committed baseline
never grows and never carries an unreviewed reason.
"""

import json
import time

import pytest

from platform_aware_scheduling_tpu.analysis import (
    Baseline,
    Finding,
    run_checks,
)
from platform_aware_scheduling_tpu.analysis.__main__ import main
from platform_aware_scheduling_tpu.analysis.core import (
    collect_pragmas,
    default_baseline_path,
)


def write_tree(root, files):
    for relpath, source in files.items():
        path = root / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(source)
    return root


# ---------------------------------------------------------------------------
# seeded violations: one MUST-flag fixture per checker
# ---------------------------------------------------------------------------


def test_clock_checker_flags_seeded_raw_clock(tmp_path):
    write_tree(tmp_path, {
        "mod.py": (
            "import time\n"
            "def tick():\n"
            "    return time.time()\n"
        ),
    })
    findings = run_checks(tmp_path, ["clock"])
    assert [f.code for f in findings] == ["raw-clock"]
    assert findings[0].path == "mod.py"
    assert findings[0].line == 3
    assert "time.time" in findings[0].symbol


def test_clock_checker_accepts_injectable_default(tmp_path):
    # the sanctioned boundary: a clock REFERENCE as a constructor default
    write_tree(tmp_path, {
        "mod.py": (
            "import time\n"
            "class Log:\n"
            "    def __init__(self, clock=time.monotonic):\n"
            "        self._clock = clock\n"
            "    def stamp(self):\n"
            "        return self._clock()\n"
        ),
    })
    assert run_checks(tmp_path, ["clock"]) == []


def test_clock_checker_exempts_perf_counter(tmp_path):
    write_tree(tmp_path, {
        "mod.py": "import time\ndef dur():\n    return time.perf_counter()\n",
    })
    assert run_checks(tmp_path, ["clock"]) == []


def test_hotpath_checker_flags_seeded_sleep_on_verb_path(tmp_path):
    # sleep two hops down the call graph: filter -> _work -> helpers.nap
    write_tree(tmp_path, {
        "helpers.py": (
            "import time\n"
            "def nap():\n"
            "    time.sleep(0.1)\n"
        ),
        "sched.py": (
            "from helpers import nap\n"
            "class Extender:\n"
            "    def filter(self, args):\n"
            "        return self._work(args)\n"
            "    def _work(self, args):\n"
            "        nap()\n"
            "        return args\n"
        ),
    })
    findings = run_checks(
        tmp_path, ["hotpath"], hotpath_roots=["sched:Extender.filter"]
    )
    assert [f.code for f in findings] == ["blocking-sleep"]
    assert findings[0].path == "helpers.py"
    # the message carries the reachability chain back to the root
    assert "filter" in findings[0].message


def test_hotpath_checker_flags_kube_verb_and_skips_thread_targets(tmp_path):
    write_tree(tmp_path, {
        "sched.py": (
            "import threading\n"
            "import time\n"
            "class Extender:\n"
            "    def filter(self, args):\n"
            "        self.kube_client.list_nodes()\n"
            "        def later():\n"
            "            time.sleep(5)\n"  # deferred: must NOT flag
            "        threading.Thread(target=later).start()\n"
        ),
    })
    findings = run_checks(
        tmp_path, ["hotpath"], hotpath_roots=["sched:Extender.filter"]
    )
    assert [f.code for f in findings] == ["blocking-kube-call"]
    assert "list_nodes" in findings[0].symbol


def test_locks_checker_flags_seeded_two_lock_inversion(tmp_path):
    write_tree(tmp_path, {
        "locked.py": (
            "import threading\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock_a = threading.Lock()\n"
            "        self._lock_b = threading.Lock()\n"
            "    def one(self):\n"
            "        with self._lock_a:\n"
            "            with self._lock_b:\n"
            "                pass\n"
            "    def two(self):\n"
            "        with self._lock_b:\n"
            "            with self._lock_a:\n"
            "                pass\n"
        ),
    })
    findings = run_checks(tmp_path, ["locks"])
    assert {f.code for f in findings} == {"lock-order"}
    assert len(findings) == 2  # one per inverted site
    assert {f.symbol.split(":", 1)[0] for f in findings} == {"S.one", "S.two"}


def test_locks_checker_flags_blocking_under_lock(tmp_path):
    write_tree(tmp_path, {
        "locked.py": (
            "import threading\n"
            "import time\n"
            "class S:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def slow(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1)\n"
        ),
    })
    findings = run_checks(tmp_path, ["locks"])
    assert [f.code for f in findings] == ["blocking-under-lock"]
    assert "time.sleep" in findings[0].symbol


def test_locks_checker_exempts_condition_wait_on_held_lock(tmp_path):
    # workqueue pattern: Condition.wait RELEASES the held lock
    write_tree(tmp_path, {
        "locked.py": (
            "import threading\n"
            "class Q:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Condition()\n"
            "    def get(self):\n"
            "        with self._lock:\n"
            "            self._lock.wait(1.0)\n"
        ),
    })
    assert run_checks(tmp_path, ["locks"]) == []


METRICS_FIXTURE = {
    "utils/trace.py": (
        "METRICS = {}\n"
        "def declare(name, kind, help_text):\n"
        "    METRICS[name] = (kind, help_text)\n"
        'declare("pas_good_total", "counter", "emitted below")\n'
        'declare("pas_dead_total", "counter", "emitted nowhere")\n'
        "class CounterSet:\n"
        "    def inc(self, name, by=1, labels=None):\n"
        "        pass\n"
        "COUNTERS = CounterSet()\n"
    ),
    "app.py": (
        "from utils import trace\n"
        "def handle():\n"
        '    trace.COUNTERS.inc("pas_good_total")\n'
        '    trace.COUNTERS.inc("pas_rogue_total")\n'
    ),
}


def test_metrics_checker_flags_seeded_undeclared_counter(tmp_path):
    write_tree(tmp_path, METRICS_FIXTURE)
    findings = run_checks(
        tmp_path, ["metrics"], metrics_inventory="utils.trace"
    )
    by_code = {f.code: f for f in findings}
    assert set(by_code) == {"undeclared-metric", "dead-metric"}
    assert "pas_rogue_total" in by_code["undeclared-metric"].symbol
    assert by_code["undeclared-metric"].path == "app.py"
    assert "pas_dead_total" in by_code["dead-metric"].symbol
    assert by_code["dead-metric"].path == "utils/trace.py"


def test_metrics_checker_skips_wrapper_parameter_names(tmp_path):
    files = dict(METRICS_FIXTURE)
    files["app.py"] = (
        "from utils import trace\n"
        'def emit(metric="pas_good_total"):\n'
        "    trace.COUNTERS.inc(metric)\n"  # name is a parameter: skip
        "def handle():\n"
        '    trace.COUNTERS.inc("pas_dead_total")\n'
    )
    write_tree(tmp_path, files)
    assert run_checks(
        tmp_path, ["metrics"], metrics_inventory="utils.trace"
    ) == []


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------


def test_pragma_suppresses_on_line_and_line_above(tmp_path):
    write_tree(tmp_path, {
        "mod.py": (
            "import time\n"
            "def tick():\n"
            "    return time.time()  # pascheck: allow[clock] -- fixture boundary\n"
            "def tock():\n"
            "    # pascheck: allow[clock] -- standalone comment above\n"
            "    return time.time()\n"
        ),
    })
    assert run_checks(tmp_path, ["clock"]) == []


def test_pragma_without_reason_is_its_own_finding(tmp_path):
    write_tree(tmp_path, {
        "mod.py": (
            "import time\n"
            "def tick():\n"
            "    return time.time()  # pascheck: allow[clock]\n"
        ),
    })
    findings = run_checks(tmp_path, ["clock"])
    # the reasonless pragma does NOT suppress, and is itself flagged
    assert sorted(f.code for f in findings) == ["bad-pragma", "raw-clock"]


def test_pragma_unknown_check_is_flagged(tmp_path):
    pragmas, findings = collect_pragmas(
        "mod.py", ["x = 1  # pascheck: allow[nonsense] -- because"]
    )
    assert [f.code for f in findings] == ["bad-pragma"]
    assert not pragmas.by_line


def test_file_level_pragma_suppresses_whole_file(tmp_path):
    write_tree(tmp_path, {
        "mod.py": (
            "# pascheck: allow-file[clock] -- fixture: whole module is a clock boundary\n"
            "import time\n"
            "def tick():\n"
            "    return time.time()\n"
            "def tock():\n"
            "    return time.monotonic()\n"
        ),
    })
    assert run_checks(tmp_path, ["clock"]) == []


def test_pragma_only_suppresses_named_check(tmp_path):
    write_tree(tmp_path, {
        "mod.py": (
            "import time\n"
            "def tick():\n"
            "    return time.time()  # pascheck: allow[metrics] -- wrong check name\n"
        ),
    })
    findings = run_checks(tmp_path, ["clock"])
    assert [f.code for f in findings] == ["raw-clock"]


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def test_baseline_round_trip_and_split(tmp_path):
    finding = Finding("clock", "raw-clock", "mod.py", 3, "tick:time.time", "m")
    other = Finding("clock", "raw-clock", "mod.py", 9, "tock:time.time", "m")
    baseline = Baseline({finding.key: "legacy boundary", "clock:gone.py:raw-clock:x:time.time": "fixed since"})
    path = tmp_path / "baseline.json"
    baseline.dump(path)
    loaded = Baseline.load(path)
    assert loaded.entries == baseline.entries
    new, accepted, stale = loaded.split([finding, other])
    assert new == [other]
    assert accepted == [finding]
    assert stale == ["clock:gone.py:raw-clock:x:time.time"]


def test_baseline_keys_are_line_independent(tmp_path):
    a = Finding("clock", "raw-clock", "mod.py", 3, "tick:time.time", "m")
    b = Finding("clock", "raw-clock", "mod.py", 300, "tick:time.time", "m")
    assert a.key == b.key  # edits that move lines don't churn the baseline


def test_baseline_rejects_reasonless_entries(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({
        "version": 1,
        "entries": [{"key": "clock:m.py:raw-clock:f:time.time", "reason": ""}],
    }))
    with pytest.raises(ValueError):
        Baseline.load(path)


# ---------------------------------------------------------------------------
# CLI exit codes
# ---------------------------------------------------------------------------


def test_cli_exit_zero_on_clean_tree(tmp_path):
    write_tree(tmp_path, {"mod.py": "def f():\n    return 1\n"})
    assert main(["--root", str(tmp_path), "--baseline", str(tmp_path / "b.json")]) == 0


def test_cli_exit_one_on_findings(tmp_path, capsys):
    write_tree(tmp_path, {"mod.py": "import time\ndef f():\n    return time.time()\n"})
    rc = main(["--root", str(tmp_path), "--baseline", str(tmp_path / "b.json")])
    assert rc == 1
    out = capsys.readouterr().out
    assert "mod.py:3" in out and "raw-clock" in out


def test_cli_exit_two_on_unknown_check(tmp_path):
    write_tree(tmp_path, {"mod.py": "def f():\n    return 1\n"})
    assert main(["--root", str(tmp_path), "--checks", "bogus"]) == 2


def test_cli_write_baseline_then_clean(tmp_path):
    write_tree(tmp_path, {"mod.py": "import time\ndef f():\n    return time.time()\n"})
    baseline = tmp_path / "b.json"
    assert main(["--root", str(tmp_path), "--baseline", str(baseline)]) == 1
    assert main(["--root", str(tmp_path), "--baseline", str(baseline), "--write-baseline"]) == 0
    # baselined: the same finding no longer fails the run
    assert main(["--root", str(tmp_path), "--baseline", str(baseline)]) == 0
    # ...but a NEW violation still does
    (tmp_path / "fresh.py").write_text("import time\ndef g():\n    return time.monotonic()\n")
    assert main(["--root", str(tmp_path), "--baseline", str(baseline)]) == 1


# ---------------------------------------------------------------------------
# the repo gate
# ---------------------------------------------------------------------------


def test_repo_is_pascheck_clean_within_budget():
    """The package as committed passes all four checkers (with the
    committed baseline) inside the 30s budget."""
    started = time.perf_counter()
    assert main([]) == 0
    assert time.perf_counter() - started < 30.0


#: the committed baseline's exact keys: adding an entry fails this test
#: by design — new code must satisfy the checkers (or carry a reviewed
#: pragma), not grow the legacy allowlist
BASELINE_KEYS = {
    "hotpath:gang/journal.py:blocking-kube-call:gang.journal:GangJournal._write:create_configmap",
    "hotpath:gang/journal.py:blocking-kube-call:gang.journal:GangJournal._write:get_configmap",
    "hotpath:gang/journal.py:blocking-kube-call:gang.journal:GangJournal._write:update_configmap",
    "hotpath:native/__init__.py:blocking-file-io:native:_so_path:open",
    "hotpath:native/__init__.py:blocking-subprocess:native:_build:subprocess.run",
    "locks:gas/scheduler.py:blocking-under-lock:GASExtender._bind_node:gas.scheduler:GASExtender._rwmutex:bind_pod",
}


def test_committed_baseline_never_grows_and_reasons_are_reviewed():
    baseline = Baseline.load(default_baseline_path())
    assert set(baseline.entries) <= BASELINE_KEYS
    for key, reason in baseline.entries.items():
        assert reason.strip(), key
        assert "UNREVIEWED" not in reason, key
        assert len(reason) >= 20, (key, "a reason must actually explain")

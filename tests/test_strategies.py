"""Strategy-layer tests (reference pkg/strategies/*/ *_test.go)."""

import pytest

from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicyRule
from platform_aware_scheduling_tpu.tas.strategies import (
    core,
    deschedule,
    dontschedule,
    scheduleonmetric,
)
from platform_aware_scheduling_tpu.testing.builders import make_node
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def metric_cache(**metrics):
    """metrics: name -> {node: value-string}"""
    cache = AutoUpdatingCache()
    for name, values in metrics.items():
        cache.write_metric(name, None)
        cache.write_metric(
            name, {n: NodeMetric(value=Quantity(v)) for n, v in values.items()}
        )
    return cache


class TestEvaluateRule:
    """operator.go:13-26 parity (reference operator_test.go)."""

    @pytest.mark.parametrize(
        "value,op,target,expected",
        [
            ("9", "LessThan", 10, True),
            ("10", "LessThan", 10, False),
            ("11", "GreaterThan", 10, True),
            ("10", "GreaterThan", 10, False),
            ("10", "Equals", 10, True),
            ("9", "Equals", 10, False),
            # milli-precision exactness
            ("9999m", "LessThan", 10, True),
            ("10001m", "GreaterThan", 10, True),
            ("10000m", "Equals", 10, True),
        ],
    )
    def test_operators(self, value, op, target, expected):
        rule = TASPolicyRule(metricname="m", operator=op, target=target)
        assert core.evaluate_rule(Quantity(value), rule) is expected

    def test_unknown_operator_raises(self):
        rule = TASPolicyRule(metricname="m", operator="Near", target=10)
        with pytest.raises(KeyError):
            core.evaluate_rule(Quantity("1"), rule)


class TestOrderedList:
    def info(self):
        return {
            "a": NodeMetric(value=Quantity("30")),
            "b": NodeMetric(value=Quantity("10")),
            "c": NodeMetric(value=Quantity("20")),
        }

    def test_greater_than_descending(self):
        out = core.ordered_list(self.info(), "GreaterThan")
        assert [m.node_name for m in out] == ["a", "c", "b"]

    def test_less_than_ascending(self):
        out = core.ordered_list(self.info(), "LessThan")
        assert [m.node_name for m in out] == ["b", "c", "a"]

    def test_other_operator_input_order(self):
        out = core.ordered_list(self.info(), "Equals")
        assert [m.node_name for m in out] == ["a", "b", "c"]


def ds_strategy(policy="pol", rules=None):
    return dontschedule.Strategy(
        policy_name=policy,
        rules=rules
        or [TASPolicyRule(metricname="filter1", operator="GreaterThan", target=10)],
    )


class TestDontSchedule:
    def test_violated_or_semantics(self):
        cache = metric_cache(
            filter1={"node1": "5", "node2": "20"},
            filter2={"node1": "100", "node2": "0"},
        )
        strategy = dontschedule.Strategy(
            policy_name="pol",
            rules=[
                TASPolicyRule("filter1", "GreaterThan", 10),
                TASPolicyRule("filter2", "GreaterThan", 50),
            ],
        )
        # node2 violates rule1, node1 violates rule2 -> both in the set
        assert set(strategy.violated(cache)) == {"node1", "node2"}

    def test_missing_metric_skipped(self):
        cache = metric_cache(filter1={"node1": "20"})
        strategy = dontschedule.Strategy(
            policy_name="pol",
            rules=[
                TASPolicyRule("missing", "GreaterThan", 10),
                TASPolicyRule("filter1", "GreaterThan", 10),
            ],
        )
        assert set(strategy.violated(cache)) == {"node1"}

    def test_equals_dedup_semantics(self):
        a = ds_strategy()
        b = ds_strategy()
        c = ds_strategy(rules=[TASPolicyRule("other", "GreaterThan", 10)])
        d = ds_strategy(policy="pol2")
        assert a.equals(b)
        assert not a.equals(c)
        assert not a.equals(d)
        # empty rule lists are never equal (reference quirk)
        assert not dontschedule.Strategy(policy_name="x").equals(
            dontschedule.Strategy(policy_name="x")
        )


class TestEnforcerRegistry:
    def test_register_add_remove(self):
        enforcer = core.MetricEnforcer()
        strategy = deschedule.Strategy(
            policy_name="p1",
            rules=[TASPolicyRule("m", "GreaterThan", 1)],
        )
        enforcer.register_strategy_type(strategy)
        assert enforcer.is_registered("deschedule")
        enforcer.add_strategy(strategy, "deschedule")
        assert len(enforcer.registered_strategies["deschedule"]) == 1
        # duplicate not added
        dup = deschedule.Strategy(
            policy_name="p1", rules=[TASPolicyRule("m", "GreaterThan", 1)]
        )
        enforcer.add_strategy(dup, "deschedule")
        assert len(enforcer.registered_strategies["deschedule"]) == 1
        enforcer.remove_strategy(dup, "deschedule")
        assert len(enforcer.registered_strategies["deschedule"]) == 0

    def test_unregistered_type_not_stored(self):
        enforcer = core.MetricEnforcer()
        strategy = ds_strategy()
        enforcer.add_strategy(strategy, "dontschedule")  # type never registered
        assert "dontschedule" not in enforcer.registered_strategies

    def test_non_enforceable_like_registration(self):
        enforcer = core.MetricEnforcer()
        s = scheduleonmetric.Strategy(
            policy_name="p", rules=[TASPolicyRule("m", "GreaterThan", 1)]
        )
        enforcer.register_strategy_type(s)
        enforcer.add_strategy(s, "scheduleonmetric")
        # scheduleonmetric implements the Enforceable protocol (no-op), so it
        # is stored, mirroring the reference where all strategies implement
        # Enforce
        assert len(enforcer.registered_strategies["scheduleonmetric"]) == 1


class TestDescheduleEnforce:
    def setup_enforcer(self):
        fake = FakeKubeClient()
        fake.add_node(make_node("node1", labels={}))
        fake.add_node(make_node("node2", labels={}))
        enforcer = core.MetricEnforcer(fake)
        strategy = deschedule.Strategy(
            policy_name="deschedule-test",
            rules=[TASPolicyRule("health_metric", "GreaterThan", 0)],
        )
        enforcer.register_strategy_type(strategy)
        enforcer.add_strategy(strategy, "deschedule")
        return fake, enforcer, strategy

    def test_enforce_labels_violating_node(self):
        fake, enforcer, strategy = self.setup_enforcer()
        cache = metric_cache(health_metric={"node1": "1", "node2": "0"})
        strategy.enforce(enforcer, cache)
        assert fake.get_node("node1").get_labels().get("deschedule-test") == "violating"
        assert "deschedule-test" not in fake.get_node("node2").get_labels()

    def test_enforce_relabels_recovered_node_null(self):
        fake, enforcer, strategy = self.setup_enforcer()
        cache = metric_cache(health_metric={"node1": "1", "node2": "0"})
        strategy.enforce(enforcer, cache)
        # node1 recovers
        cache2 = metric_cache(health_metric={"node1": "0", "node2": "0"})
        strategy.enforce(enforcer, cache2)
        # reference parity: label flips to "null", not removed (enforce.go:118-132)
        assert fake.get_node("node1").get_labels().get("deschedule-test") == "null"

    def test_cleanup_removes_labels(self):
        fake, enforcer, strategy = self.setup_enforcer()
        cache = metric_cache(health_metric={"node1": "1", "node2": "0"})
        strategy.enforce(enforcer, cache)
        strategy.cleanup(enforcer, "deschedule-test")
        assert "deschedule-test" not in fake.get_node("node1").get_labels()

    def test_enforce_returns_actual_violation_count(self):
        """Regression (ISSUE 4): the count used to be incremented inside
        the NON-violated policy loop, so with one registered policy and
        three nodes (one violating) enforce() returned 2 — the number of
        non-violating registered policies per node — instead of 1."""
        fake, enforcer, strategy = self.setup_enforcer()
        fake.add_node(make_node("node3", labels={}))
        cache = metric_cache(
            health_metric={"node1": "1", "node2": "0", "node3": "0"}
        )
        assert strategy.enforce(enforcer, cache) == 1
        # two violating nodes -> 2
        cache2 = metric_cache(
            health_metric={"node1": "1", "node2": "1", "node3": "0"}
        )
        assert strategy.enforce(enforcer, cache2) == 2
        # no violations -> 0 (the old code would have returned 3)
        cache3 = metric_cache(
            health_metric={"node1": "0", "node2": "0", "node3": "0"}
        )
        assert strategy.enforce(enforcer, cache3) == 0

    def test_enforce_publishes_violations_each_cycle(self):
        """Every enforcement pass publishes its node -> [policies] map to
        the enforcer's violation observers — including the empty map, so
        hysteresis streaks downstream can reset on clean cycles."""
        fake, enforcer, strategy = self.setup_enforcer()
        seen = []
        enforcer.violation_observers.append(
            lambda stype, violations: seen.append((stype, violations))
        )
        strategy.enforce(
            enforcer, metric_cache(health_metric={"node1": "1", "node2": "0"})
        )
        strategy.enforce(
            enforcer, metric_cache(health_metric={"node1": "0", "node2": "0"})
        )
        assert seen == [
            ("deschedule", {"node1": ["deschedule-test"]}),
            ("deschedule", {}),
        ]

    def test_periodic_enforcement_loop(self):
        import time

        fake, enforcer, strategy = self.setup_enforcer()
        cache = metric_cache(health_metric={"node1": "1", "node2": "0"})
        stop = enforcer.start_enforcing(cache, 0.02)
        try:
            deadline = time.time() + 2
            while time.time() < deadline:
                if fake.get_node("node1").get_labels().get("deschedule-test") == "violating":
                    break
                time.sleep(0.01)
            assert (
                fake.get_node("node1").get_labels().get("deschedule-test")
                == "violating"
            )
        finally:
            stop.set()

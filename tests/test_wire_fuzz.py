"""Differential wire fuzzing: the native scanner path and the exact
Python path must produce IDENTICAL responses for every body (VERDICT r4
missing #2 / task #3).

Oracle: one MetricsExtender over one seeded cache+mirror; each fuzz body
is served twice through the REAL verb handlers — once with the native
scanner available, once with ``get_wirec`` patched to None (the exact
path that owns every decode-failure/empty-list wire quirk,
telemetryscheduler.py module doc).  Status and body bytes must match
exactly, for Prioritize and Filter, in both nodeCacheCapable modes.
A body the scanner rejects (strict parse) must therefore produce the
exact path's answer on BOTH runs — so any scanner-vs-Python divergence
in acceptance, field resolution, case folding, escape handling, or
response assembly shows up as a byte diff.

Corpus: >=10,000 cases from a FIXED seed —
  * structured generator over the wire grammar: upstream + reference key
    spellings and case variants, duplicate/null fields in document order,
    Nodes/NodeNames/both/neither, escaped + non-ASCII + empty + duplicate
    node names, pods with/without the telemetry-policy label, unknown
    policies, extra unknown fields, nested metadata oddities;
  * byte-level mutations (truncate / flip / insert / delete / splice) of
    the golden request fixtures (tests/golden/*.json) and of generated
    valid bodies — mostly-invalid inputs that must fail IDENTICALLY.

Divergence log (kept per the task's done-criterion):
  * **REAL divergence found by this harness on its first run** (round 5,
    generated case #1756): a ``Nodes.items`` entry with NO
    ``metadata.name`` (``{}``) was DROPPED from the candidate set by the
    native scanner but scored as the empty-named node ``""`` by the
    Python path (``Node({}).name == ""`` — the Go zero value, which is
    what the reference's decode produces).  Fixed in wirec.c
    ``scan_node_item``: a missing name is now a present empty slice; a
    NON-string name stays a no-match on both paths; non-object node
    metadata fails the native parse (Go decode error) so the exact path
    owns it.  Pinned by test_wirec.py
    ``test_missing_name_is_empty_string_candidate``.
  * same sweep hardened ``KubeObject.metadata`` against JSON null
    (Go: null into a struct "has no effect"; the Python property used to
    raise on ``metadata: null`` bodies).
  * a second divergence class was closed while building the harness:
    ``str.lower()`` key folding on the Python path folds non-ASCII
    spellings into ASCII the native byte tables never match — fixed in
    extender/types.py (A-Z-only fold, r4 advisor finding); the generator
    keeps emitting such keys (``_exotic_key``) so a regression reopens
    as a byte diff here.
  * after the fixes: the full >=10k corpus passes with zero divergence.
"""

from __future__ import annotations

import json
import os
import random

import numpy as np
import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas import telemetryscheduler
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.utils.quantity import Quantity

pytestmark = pytest.mark.skipif(
    get_wirec() is None, reason="native scanner unavailable (no compiler)"
)

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "golden")
NUM_NODES = 64
CASES_GENERATED = 6_000
CASES_MUTATED = 4_500


def _policy_obj(name):
    return {
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "strategies": {
                "scheduleonmetric": {
                    "rules": [
                        {
                            "metricname": "fuzz_metric",
                            "operator": "GreaterThan",
                            "target": 0,
                        }
                    ]
                },
                "dontschedule": {
                    "rules": [
                        {
                            "metricname": "fuzz_metric",
                            "operator": "GreaterThan",
                            "target": 700_000,
                        }
                    ]
                },
            }
        },
    }


# name alphabet stresses every encoder branch: escapes, non-ASCII,
# multibyte UTF-8, JSON-meta characters
NAME_POOL = (
    [f"node-{i:03d}" for i in range(40)]
    + ['no"de-q', "no\\de-b", "node\t-t", "nöde-ü", "节点-一", "n💡de"]
    + ["", " ", "trailing ", "x" * 300]
)


@pytest.fixture(scope="module", params=[True, False], ids=["ncc", "legacy"])
def service(request):
    """(extender, known node names) over a seeded cache+mirror; half the
    NAME_POOL is interned with metric values so requests mix known and
    unknown candidates.  Parametrized over BOTH nodeCacheCapable modes
    (the False mode exercises the NodeNames-ignoring legacy quirks); the
    legacy mode runs a reduced slice of the corpus — the mode only
    changes candidate-carrier selection, not parse/encode shapes."""
    rng = np.random.default_rng(7)
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default", "fuzz-pol", TASPolicy.from_obj(_policy_obj("fuzz-pol"))
    )
    known = NAME_POOL[: len(NAME_POOL) // 2 * 2 : 2] + [
        f"node-{i:03d}" for i in range(40)
    ]
    values = rng.integers(0, 1_000_000, size=len(known))
    cache.write_metric(
        "fuzz_metric",
        {
            n: NodeMetric(value=Quantity(int(v)))
            for n, v in zip(known, values)
        },
    )
    ext = MetricsExtender(
        cache, mirror=mirror, node_cache_capable=request.param
    )
    return ext, known


def _case_counts(ext) -> tuple:
    """(generated, mutated) case counts: the primary ncc mode runs the
    full >=10k corpus; the legacy mode a reduced slice."""
    if ext.node_cache_capable:
        return CASES_GENERATED, CASES_MUTATED
    return 2_000, 1_500


def _request(body: bytes, path: str) -> HTTPRequest:
    return HTTPRequest(
        method="POST",
        path=path,
        headers={"Content-Type": "application/json"},
        body=body,
    )


def _serve_both(ext, body: bytes, verb: str, monkeypatch):
    """(native response, exact-path response) through the real verb."""
    handler = getattr(ext, verb)
    path = f"/scheduler/{verb}"
    native = handler(_request(body, path))
    with monkeypatch.context() as m:
        m.setattr(telemetryscheduler, "get_wirec", lambda: None)
        exact = handler(_request(body, path))
    return native, exact


def _exotic_key(rng: random.Random, base: str) -> str:
    """Key spellings around the ASCII-fold contract: plain case variants
    plus non-ASCII lookalikes (Kelvin sign K, long s ſ) that Go's
    EqualFold would accept but BOTH paths here must drop identically."""
    roll = rng.random()
    if roll < 0.4:
        return "".join(
            c.upper() if rng.random() < 0.5 else c.lower() for c in base
        )
    if roll < 0.5 and "k" in base.lower():
        return base.lower().replace("k", "K", 1)  # KELVIN SIGN
    if roll < 0.6 and "s" in base.lower():
        return base.lower().replace("s", "ſ", 1)  # LONG S
    return base


def _rand_name(rng: random.Random) -> str:
    if rng.random() < 0.7:
        return rng.choice(NAME_POOL)
    return "".join(
        rng.choice('abz-09 "\\\té一\U0001f4a1')
        for _ in range(rng.randrange(0, 12))
    )


def _gen_body(rng: random.Random) -> bytes:
    """One structured body over the wire grammar (module doc)."""
    parts = []
    # Pod
    if rng.random() < 0.9:
        labels = {}
        if rng.random() < 0.8:
            label_key = (
                "telemetry-policy"
                if rng.random() < 0.9
                else rng.choice(["telemetry-Policy", "policy", ""])
            )
            labels[label_key] = rng.choice(
                ["fuzz-pol", "no-such-pol", "", 'p"ol', "pöl"]
            )
        pod = {
            "metadata": {
                "name": rng.choice(["p", "", 'p"od', "p二"]),
                "namespace": rng.choice(["default", "", "other", "déf"]),
                "labels": labels,
            }
        }
        if rng.random() < 0.1:
            pod["spec"] = {"nodeName": "x", "containers": []}
        if rng.random() < 0.1:
            pod["metadata"]["extra"] = [1, {"deep": None}]
        parts.append((_exotic_key(rng, "Pod"), pod))
    # candidate carriers: Nodes / NodeNames / both / neither, null forms
    names = [_rand_name(rng) for _ in range(rng.randrange(0, 14))]
    if rng.random() < 0.15:
        names = names + names  # duplicates
    carrier = rng.random()
    if carrier < 0.45:
        items = [
            {"metadata": {"name": n}}
            if rng.random() < 0.85
            else rng.choice(
                [
                    {},
                    {"metadata": {}},
                    {"metadata": {"name": n, "labels": {"a": "b"}}},
                    {"status": {"phase": "Ready"}},
                ]
            )
            for n in names
        ]
        nodes = (
            None
            if rng.random() < 0.1
            else {"items": items if rng.random() < 0.9 else None}
        )
        parts.append((_exotic_key(rng, "Nodes"), nodes))
    elif carrier < 0.85:
        value = None if rng.random() < 0.1 else names
        parts.append((_exotic_key(rng, "NodeNames"), value))
    elif carrier < 0.95:
        parts.append((_exotic_key(rng, "Nodes"), {"items": []}))
        parts.append((_exotic_key(rng, "NodeNames"), names))
    # (else: neither carrier)
    if rng.random() < 0.15:  # duplicate field, later wins in Go order
        key, value = rng.choice(parts) if parts else ("Pod", {})
        parts.append((_exotic_key(rng, key), value))
    if rng.random() < 0.1:
        parts.append(("Unknown" + str(rng.randrange(3)), [None, 1, "x"]))
    rng.shuffle(parts)
    obj = "{" + ", ".join(
        json.dumps(k, ensure_ascii=rng.random() < 0.5)
        + ": "
        + json.dumps(v, ensure_ascii=rng.random() < 0.5)
        for k, v in parts
    ) + "}"
    return obj.encode()


def _mutate(rng: random.Random, body: bytes) -> bytes:
    data = bytearray(body)
    for _ in range(rng.randrange(1, 4)):
        if not data:
            break
        op = rng.random()
        pos = rng.randrange(len(data))
        if op < 0.3:  # truncate
            del data[pos:]
        elif op < 0.5:  # byte flip
            data[pos] = rng.randrange(256)
        elif op < 0.7:  # insert json-meta byte
            data.insert(pos, ord(rng.choice('{}[]",:\\ ')))
        elif op < 0.85:  # delete a span
            del data[pos : pos + rng.randrange(1, 6)]
        else:  # splice a fragment from elsewhere in the body
            frag = bytes(data[pos : pos + 8])
            at = rng.randrange(len(data) + 1)
            data[at:at] = frag
    return bytes(data)


def _assert_same(native, exact, body: bytes, verb: str):
    assert native.status == exact.status and native.body == exact.body, (
        f"{verb} divergence on {body[:200]!r}...: "
        f"native {native.status}/{native.body[:120]!r} vs "
        f"exact {exact.status}/{exact.body[:120]!r}"
    )


class TestDifferentialWireFuzz:
    def test_generated_corpus(self, service, monkeypatch):
        ext, _ = service
        count, _ = _case_counts(ext)
        rng = random.Random(0xC0FFEE)
        for i in range(count):
            body = _gen_body(rng)
            verb = "prioritize" if i % 2 == 0 else "filter"
            native, exact = _serve_both(ext, body, verb, monkeypatch)
            _assert_same(native, exact, body, verb)

    def test_mutated_corpus(self, service, monkeypatch):
        ext, _ = service
        _, count = _case_counts(ext)
        rng = random.Random(0xFEED)
        goldens = [
            open(os.path.join(GOLDEN_DIR, f), "rb").read()
            for f in sorted(os.listdir(GOLDEN_DIR))
            if f.endswith(".json")
        ]
        assert goldens, "golden request fixtures missing"
        seeds = goldens + [_gen_body(rng) for _ in range(40)]
        for i in range(count):
            body = _mutate(rng, rng.choice(seeds))
            verb = "prioritize" if i % 2 == 0 else "filter"
            native, exact = _serve_both(ext, body, verb, monkeypatch)
            _assert_same(native, exact, body, verb)

    def test_corpus_size_documented(self):
        assert CASES_GENERATED + CASES_MUTATED >= 10_000

    def test_exotic_fold_key_dropped_identically(self, service, monkeypatch):
        """The ASCII-fold contract pinned explicitly: a LONG-S spelling
        of NodeNames (``NodeName\u017f``, which Go's EqualFold would
        accept as the field) is NOT this field on either path here, so
        the body has no candidate carrier and both paths answer with the
        empty-200 quirk."""
        ext, known = service
        body = json.dumps(
            {
                "Pod": {
                    "metadata": {
                        "name": "p",
                        "namespace": "default",
                        "labels": {"telemetry-policy": "fuzz-pol"},
                    }
                },
                "NodeName\u017f": [known[0]],
            }
        ).encode()
        native, exact = _serve_both(ext, body, "prioritize", monkeypatch)
        _assert_same(native, exact, body, "prioritize")
        # no recognized candidate carrier -> the empty-200 quirk
        assert native.status == 200 and native.body == b""

"""Controller-loop instrumentation (ISSUE 3 satellite): WorkQueue
depth/adds/retries/done counters and the work-latency histogram under
rate-limited re-adds, and Informer relist/watch-error counters plus the
has_synced gauge transitions on a failing ListWatch.  All hermetic:
private CounterSet/LatencyRecorder per test, no global state."""

import threading
import time

from platform_aware_scheduling_tpu.kube.informer import Informer, ListWatch
from platform_aware_scheduling_tpu.kube.workqueue import WorkQueue
from platform_aware_scheduling_tpu.utils.tracing import (
    CounterSet,
    LatencyRecorder,
)


def _queue(**kwargs):
    counters = CounterSet()
    recorder = LatencyRecorder()
    queue = WorkQueue(
        name="testq", counters=counters, recorder=recorder, **kwargs
    )
    return queue, counters, recorder


QL = {"queue": "testq"}


class TestWorkQueueCounters:
    def test_adds_depth_done_roundtrip(self):
        queue, counters, recorder = _queue()
        for i in range(3):
            queue.add(f"item-{i}")
        assert counters.get("pas_workqueue_adds_total", labels=QL) == 3
        assert counters.get(
            "pas_workqueue_depth", kind="gauge", labels=QL
        ) == 3
        # duplicate while pending: deduped, no extra add
        queue.add("item-0")
        assert counters.get("pas_workqueue_adds_total", labels=QL) == 3
        for _ in range(3):
            item, shutdown = queue.get(timeout=1)
            assert not shutdown
            time.sleep(0.002)  # measurable work latency
            queue.done(item)
        assert counters.get("pas_workqueue_done_total", labels=QL) == 3
        assert counters.get(
            "pas_workqueue_depth", kind="gauge", labels=QL
        ) == 0
        summary = recorder.summary("workqueue_work")
        assert summary["count"] == 3
        assert summary["p50"] > 0

    def test_rate_limited_readds_count_retries(self):
        queue, counters, _recorder = _queue(base_delay=0.001, max_delay=0.01)
        queue.add("flaky")
        item, _ = queue.get(timeout=1)
        queue.done(item)
        for _ in range(3):
            queue.add_rate_limited("flaky")
            item, _ = queue.get(timeout=1)
            assert item == "flaky"
            queue.done(item)
        assert counters.get("pas_workqueue_retries_total", labels=QL) == 3
        assert counters.get("pas_workqueue_adds_total", labels=QL) == 4
        assert counters.get("pas_workqueue_done_total", labels=QL) == 4

    def test_readd_while_processing_requeues_and_counts(self):
        queue, counters, _recorder = _queue()
        queue.add("hot")
        item, _ = queue.get(timeout=1)
        queue.add("hot")  # re-added while processing: dirty, not queued
        assert counters.get(
            "pas_workqueue_depth", kind="gauge", labels=QL
        ) == 0
        queue.done(item)  # done re-queues the dirty item
        assert counters.get(
            "pas_workqueue_depth", kind="gauge", labels=QL
        ) == 1
        item, _ = queue.get(timeout=1)
        queue.done(item)
        assert counters.get("pas_workqueue_done_total", labels=QL) == 2

    def test_unnamed_queue_stays_silent(self):
        counters = CounterSet()
        queue = WorkQueue(counters=counters)
        queue.add("x")
        item, _ = queue.get(timeout=1)
        queue.done(item)
        assert counters.prometheus_text() == ""


class TestInformerCounters:
    def test_synced_gauge_transitions_and_relists_count(self):
        counters = CounterSet()
        labels = {"informer": "testinf"}
        done_watching = threading.Event()

        def list_func():
            return [{"name": "a"}], "rv1"

        def watch_func(_rv):
            done_watching.set()
            threading.Event().wait(5)  # hold the watch open (daemon thread)
            return iter(())

        informer = Informer(
            ListWatch(list_func, watch_func, lambda obj: obj["name"]),
            name="testinf",
            counters=counters,
        )
        assert counters.get(
            "pas_informer_synced", kind="gauge", labels=labels
        ) == 0
        informer.start()
        try:
            assert informer.wait_for_cache_sync(5)
            assert done_watching.wait(5)
            assert counters.get(
                "pas_informer_synced", kind="gauge", labels=labels
            ) == 1
            assert counters.get(
                "pas_informer_relists_total", labels=labels
            ) >= 1
        finally:
            informer.stop()

    def test_failing_listwatch_counts_watch_errors(self):
        counters = CounterSet()
        labels = {"informer": "flaky"}
        attempts = {"n": 0}

        def list_func():
            attempts["n"] += 1
            if attempts["n"] <= 2:
                raise ConnectionError("apiserver away")
            return [{"name": "a"}], "rv1"

        def watch_func(_rv):
            threading.Event().wait(5)  # hold the watch open (daemon thread)
            return iter(())

        informer = Informer(
            ListWatch(list_func, watch_func, lambda obj: obj["name"]),
            name="flaky",
            counters=counters,
        )
        informer.start()
        try:
            # two failed lists (counted as watch errors + backoff) before
            # the third succeeds and flips the synced gauge
            assert informer.wait_for_cache_sync(10)
            assert counters.get(
                "pas_informer_watch_errors_total", labels=labels
            ) == 2
            assert counters.get(
                "pas_informer_relists_total", labels=labels
            ) >= 3
            assert counters.get(
                "pas_informer_synced", kind="gauge", labels=labels
            ) == 1
        finally:
            informer.stop()

    def test_repeated_watch_failures_back_off_with_jitter(self):
        """ISSUE 5 satellite: K consecutive ListWatch failures must space
        their relists with growing, capped, deterministically-jittered
        delays — not the old fixed 0.2 s relist hammer — while the
        watch-error counter keeps moving."""
        from platform_aware_scheduling_tpu.kube.retry import (
            backoff_delay,
            stable_hash,
        )

        counters = CounterSet()
        labels = {"informer": "storm"}
        fails = 6
        attempts = {"n": 0}
        done = threading.Event()

        def list_func():
            attempts["n"] += 1
            if attempts["n"] <= fails:
                raise ConnectionError("apiserver away")
            done.set()
            return [], "rv1"

        def watch_func(_rv):
            threading.Event().wait(5)  # hold the watch open (daemon thread)
            return iter(())

        informer = Informer(
            ListWatch(list_func, watch_func, lambda obj: obj["name"]),
            name="storm",
            counters=counters,
            relist_backoff_base_s=0.001,
            relist_backoff_max_s=0.008,
        )
        informer.start()
        try:
            assert done.wait(10)
            assert counters.get(
                "pas_informer_watch_errors_total", labels=labels
            ) == fails
            backoffs = list(informer.relist_backoffs)
            assert len(backoffs) == fails
            # the exact deterministic schedule: capped exponential with
            # seeded jitter off the informer name
            expected = [
                backoff_delay(n, 0.001, 0.008, seed=stable_hash("storm"))
                for n in range(1, fails + 1)
            ]
            assert backoffs == expected
            assert max(backoffs) <= 0.008  # capped
            # pre-jitter schedule grows to the cap; jitter keeps every
            # delay within [0.5, 1.0) of it
            assert backoffs[0] < 0.001 and backoffs[-1] >= 0.004
        finally:
            informer.stop()

    def test_event_delivery_resets_backoff_streak(self):
        """A watch that delivered an event is healthy again: the next
        failure pays the BASE delay, not the accumulated cap."""
        counters = CounterSet()
        rounds = {"n": 0}

        def list_func():
            return [{"name": "a"}], "rv1"

        def watch_func(_rv):
            rounds["n"] += 1
            if rounds["n"] <= 4:
                def broken():
                    yield ("MODIFIED", {"name": "a"})
                    raise ConnectionError("reset")

                return broken()
            threading.Event().wait(5)
            return iter(())

        informer = Informer(
            ListWatch(list_func, watch_func, lambda obj: obj["name"]),
            name="flappy",
            counters=counters,
            relist_backoff_base_s=0.001,
            relist_backoff_max_s=1.0,
        )
        informer.start()
        try:
            deadline = time.monotonic() + 10
            while rounds["n"] <= 4 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert rounds["n"] > 4
            # every failure followed a delivered event -> streak reset to
            # 1 each time -> all four delays identical (the base tier)
            backoffs = list(informer.relist_backoffs)
            assert len(backoffs) == 4
            assert len(set(backoffs)) == 1
        finally:
            informer.stop()

    def test_unnamed_informer_stays_silent(self):
        counters = CounterSet()
        def watch_func(_rv):
            threading.Event().wait(5)  # hold the watch open (daemon thread)
            return iter(())

        informer = Informer(
            ListWatch(lambda: ([], ""), watch_func, lambda obj: str(obj)),
            counters=counters,
        )
        informer.start()
        try:
            assert informer.wait_for_cache_sync(5)
            assert counters.prometheus_text() == ""
        finally:
            informer.stop()

"""The causal event spine (utils/events.py) and its query surface:
journal bounds/ordering under writer torture, one-hop correlation walks,
the /debug/explain wire contract on BOTH front-ends (404 while disabled,
405 non-GET, 400 without a filter, queue bypass on the async server),
and the TraceBuffer slowest-top-K under concurrent completions.

Everything is hermetic: unit tests run on private EventJournal/
TraceBuffer instances; wire tests run in-process servers on 127.0.0.1
ephemeral ports seeded like benchmarks/http_load.
"""

import json
import threading
import time

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.events import EventJournal, JOURNAL
from wirehelpers import (
    get_request as _get,
    post_bytes as _post,
    raw_request as _raw,
    start_async as _start_async,
    start_threaded as _start_threaded,
)


class TestEventJournal:
    def test_bounded_with_drop_accounting(self):
        journal = EventJournal(capacity=16)
        for i in range(50):
            journal.publish("wire", "filter responded", pod=f"ns/p-{i}")
        assert len(journal) == 16
        assert journal.dropped == 50 - 16
        # the ring keeps the NEWEST events (drop-oldest overflow)
        kept = [r["pod"] for r in journal.snapshot()]
        assert kept == [f"ns/p-{i}" for i in range(34, 50)]

    def test_disabled_publishes_nothing(self):
        journal = EventJournal(capacity=8)
        journal.configure(enabled=False)
        journal.publish("wire", "filter responded", pod="ns/p")
        assert len(journal) == 0 and journal.dropped == 0
        journal.configure(enabled=True)
        journal.publish("wire", "filter responded", pod="ns/p")
        assert len(journal) == 1

    def test_reconfigure_capacity_keeps_tail(self):
        journal = EventJournal(capacity=32)
        for i in range(32):
            journal.publish("admission", "enqueue", pod=f"ns/p-{i}")
        journal.configure(capacity=4)
        assert len(journal) == 4
        assert [r["pod"] for r in journal.snapshot()] == [
            f"ns/p-{i}" for i in range(28, 32)
        ]

    def test_explain_walks_one_hop(self):
        """pod -> gang -> the preemption event that never names the pod:
        the one-hop expansion is what joins a wire span to the
        preemption that seated it."""
        journal = EventJournal()
        journal.publish(
            "admission", "enqueue", pod="default/high-0", gang="gang-high"
        )
        journal.publish(
            "preemption", "planned", gang="gang-high",
            data={"victims": ["batch-a"]},
        )
        journal.publish("wire", "filter responded", pod="default/other")
        out = journal.explain(pod="default/high-0")
        kinds = [r["kind"] for r in out["events"]]
        assert kinds == ["admission", "preemption"]
        assert out["correlated"]["gangs"] == ["gang-high"]
        assert len(out["narrative"]) == 2
        assert "victims=['batch-a']" in out["narrative"][1]

    def test_concurrent_writers_bounded_and_ordered(self):
        """Writer torture: the ring stays hard-bounded, every publish is
        accounted (kept + dropped), seq is globally unique, and each
        writer's events appear in its own publish order."""
        journal = EventJournal(capacity=256)
        writers, per_writer = 8, 500
        barrier = threading.Barrier(writers)

        def hammer(w):
            barrier.wait()
            for i in range(per_writer):
                journal.publish(
                    "wire", "filter responded",
                    pod=f"ns/w{w}", data={"i": i},
                )

        threads = [
            threading.Thread(target=hammer, args=(w,))
            for w in range(writers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        snap = journal.snapshot()
        assert len(snap) == 256
        assert journal.dropped == writers * per_writer - 256
        seqs = [r["seq"] for r in snap]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        for w in range(writers):
            mine = [r["data"]["i"] for r in snap if r["pod"] == f"ns/w{w}"]
            assert mine == sorted(mine)


class TestTraceBufferConcurrentCompletion:
    def test_torture_bounded_and_slowest_sorted(self):
        """Many completing requests racing into one TraceBuffer: the
        recent ring and the top-K stay hard-bounded, the top-K comes out
        duration-sorted, and it holds exactly the globally slowest
        spans (each writer plants one known outlier)."""
        buf = trace.TraceBuffer(capacity=128, slow_capacity=8)
        writers, per_writer = 8, 200
        barrier = threading.Barrier(writers)

        def hammer(w):
            barrier.wait()
            for i in range(per_writer):
                span = trace.Span("POST /t", f"w{w}-{i}")
                # deterministic durations; one per-writer outlier
                span.duration_s = 10.0 + w if i == 7 else (i % 50) * 1e-4
                span.status = 200
                buf.add(span)

        threads = [
            threading.Thread(target=hammer, args=(w,))
            for w in range(writers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert len(buf) == 128
        snap = buf.snapshot()
        slow = snap["slowest"]
        assert len(slow) == 8
        durations = [s["duration_ms"] for s in slow]
        assert durations == sorted(durations, reverse=True)
        # the 8 planted outliers (10..17 s) beat every organic span
        assert sorted(s["id"] for s in slow) == [
            f"w{w}-7" for w in range(writers)
        ]


class _ExplainContract:
    """The /debug/explain wire contract, shared by both front-ends."""

    start = None  # front-end starter, set by subclasses

    def _server(self):
        ext, names = build_extender(16, device=True)
        return type(self).start(ext), names

    def test_contract(self):
        server, names = self._server()
        JOURNAL.reset()
        try:
            # no filter -> 400 with a usage hint
            status, _, body = _get(server.port, "/debug/explain")
            assert status == 400 and b"required" in body
            # non-GET -> 405
            status, _, _ = _raw(
                server.port, _post("/debug/explain?pod=x", b"{}")
            )
            assert status == 405
            # disabled journal -> 404 (the --events=off contract)
            JOURNAL.configure(enabled=False)
            try:
                status, _, body = _get(
                    server.port, "/debug/explain?pod=x"
                )
                assert status == 404 and b"disabled" in body
            finally:
                JOURNAL.configure(enabled=True)
            # drive one real verb with a caller-chosen request id, then
            # ask the spine about it: the wire event must come back
            # under ?request_id= AND under ?pod=
            body_bytes = make_bodies(names, "nodenames", count=1)[0]
            pod = json.loads(body_bytes)["Pod"]["metadata"]
            pod_key = f"{pod['namespace']}/{pod['name']}"
            status, _, _ = _raw(
                server.port,
                _post(
                    "/scheduler/prioritize", body_bytes,
                    extra="X-Request-ID: explain-rid-1\r\n",
                ),
            )
            assert status == 200
            # the wire event publishes when the span lands in TRACES —
            # just AFTER the response bytes go out; poll briefly so the
            # reader never races the writer (test_observability.py
            # _wait_for_span does the same)
            deadline = time.time() + 5.0
            while True:
                status, _, body = _get(
                    server.port,
                    "/debug/explain?request_id=explain-rid-1",
                )
                assert status == 200
                out = json.loads(body)
                if any(
                    e["kind"] == "wire"
                    and e["event"] == "prioritize responded"
                    and e["request_id"] == "explain-rid-1"
                    for e in out["events"]
                ):
                    break
                assert time.time() < deadline, out
                time.sleep(0.005)
            status, _, body = _get(
                server.port, f"/debug/explain?pod={pod_key}"
            )
            assert status == 200
            out = json.loads(body)
            assert any(
                e["request_id"] == "explain-rid-1" for e in out["events"]
            )
            assert out["narrative"]
        finally:
            server.shutdown()
            JOURNAL.reset()


class TestExplainThreaded(_ExplainContract):
    start = staticmethod(_start_threaded)


class TestExplainAsync(_ExplainContract):
    start = staticmethod(_start_async)

    def test_bypasses_admission_queue(self):
        """/debug/explain is in DEBUG_ENDPOINTS, so the async front-end
        serves it off the event loop even while the verb queue is
        saturated — the same inheritance /debug/traces gets."""
        from platform_aware_scheduling_tpu.serving.http import (
            QUEUE_BYPASS_PATHS,
        )

        assert "/debug/explain" in QUEUE_BYPASS_PATHS

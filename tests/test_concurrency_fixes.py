"""Regression tests for the round-1 advisor findings: mirror-attach lock
ordering, informer resync serialization, atomic planner snapshots, and
Sinkhorn handling of fully-ineligible pods."""

import threading
import time

import numpy as np
import pytest

from platform_aware_scheduling_tpu.gas.cache import ADD, REMOVE, Cache
from platform_aware_scheduling_tpu.gas.device import DeviceBinpacker
from platform_aware_scheduling_tpu.kube.informer import Informer, ListWatch
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.planner import BatchPlanner
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.testing.builders import (
    make_node,
    make_policy,
    make_pod,
    rule,
)
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def gpu_node(name, cards=2):
    return make_node(
        name,
        labels={"gpu.intel.com/cards": ".".join(f"card{i}" for i in range(cards))},
        allocatable={
            "gpu.intel.com/i915": str(cards),
            "gpu.intel.com/millicores": "2000",
        },
    )


def gpu_pod(name, node_name=""):
    return make_pod(
        name,
        container_requests=[{
            "gpu.intel.com/i915": "1",
            "gpu.intel.com/millicores": "100",
        }],
        node_name=node_name,
    )


class TestMirrorAttachLockOrder:
    def test_attach_replays_existing_bookings(self):
        """A mirror constructed against a cache that already carries
        bookings must see them (replay happens inside hook registration)."""
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1"))
        cache = Cache(kube, start=False)
        cache.adjust_pod_resources_locked(
            gpu_pod("p0", node_name="n1"), ADD, "card0", "n1"
        )
        packer = DeviceBinpacker(cache, use_mirror=True)
        mirror = packer.mirror
        with mirror._lock:
            row = mirror._node_index["n1"]
            assert mirror._used[row].sum() > 0

    def test_construction_races_cache_worker_without_deadlock(self):
        """ABBA regression: constructing a mirror while the cache worker is
        firing booking hooks must not deadlock (advisor r1, medium).  The
        old code replayed bookings cache-lock-free after registering the
        hook — mirror→cache order against the worker's cache→mirror."""
        kube = FakeKubeClient()
        kube.add_node(gpu_node("n1"))
        cache = Cache(kube, start=False)
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                pod = gpu_pod(f"c{i % 4}", node_name="n1")
                cache.adjust_pod_resources_locked(pod, ADD, "card0", "n1")
                cache.adjust_pod_resources_locked(pod, REMOVE, "card0", "n1")
                i += 1

        churner = threading.Thread(target=churn, daemon=True)
        churner.start()
        done = threading.Event()

        def construct():
            for _ in range(20):
                DeviceBinpacker(cache, use_mirror=True)
            done.set()

        builder = threading.Thread(target=construct, daemon=True)
        builder.start()
        finished = done.wait(timeout=30)
        stop.set()
        churner.join(timeout=5)
        assert finished, "mirror construction deadlocked against cache worker"


class TestInformerResyncSerialization:
    def _informer(self, objects, on_update, on_delete=None):
        store = {k: v for k, v in objects.items()}
        return Informer(
            ListWatch(
                lambda: (list(store.values()), ""),
                lambda rv: iter(()),
                lambda obj: obj["name"],
            ),
            on_update=on_update,
            on_delete=on_delete,
            resync_period=3600.0,
        )

    def test_resync_skips_concurrently_deleted_key(self):
        """A resync pass must not re-deliver update(obj, obj) for an object
        deleted since its snapshot — that transiently resurrected deleted
        state in subscribers (advisor r1)."""
        a, b = {"name": "a"}, {"name": "b"}
        delivered = []

        def on_update(old, new):
            delivered.append(new["name"])
            if new["name"] == "a":
                # simulate the watch thread deleting b mid-resync: the
                # dispatch lock serializes us, so the store mutation lands
                # before the resync pass reaches b
                with informer._store_lock:
                    informer._store.pop("b", None)

        informer = self._informer({"a": a, "b": b}, on_update)
        informer._relist(initial=True)
        informer._resync_once()
        assert delivered == ["a"]

    def test_resync_delivers_current_object_not_snapshot(self):
        """An object replaced since the resync snapshot is re-delivered at
        its current value, never the stale one."""
        a_old = {"name": "a", "v": 1}
        a_new = {"name": "a", "v": 2}
        seen = []
        informer = self._informer({"a": a_old}, lambda old, new: seen.append(new))
        informer._relist(initial=True)
        with informer._store_lock:
            informer._store["a"] = a_new
        informer._resync_once()
        assert seen == [a_new]


class TestPlannerAtomicSnapshot:
    def _build(self):
        cache = AutoUpdatingCache()
        mirror = TensorStateMirror()
        mirror.attach(cache)
        planner = BatchPlanner(cache, mirror, node_capacity=5)
        cache.write_policy(
            "default",
            "plan-pol",
            TASPolicy.from_obj(
                make_policy(
                    "plan-pol",
                    strategies={
                        "scheduleonmetric": [rule("m", "GreaterThan", 0)],
                        "dontschedule": [rule("m", "GreaterThan", 900)],
                    },
                )
            ),
        )
        cache.write_metric(
            "m",
            {n: NodeMetric(value=Quantity(str(v)))
             for n, v in {"n1": 100, "n2": 50}.items()},
        )
        return cache, mirror, planner

    def test_replan_takes_one_snapshot(self):
        """replan resolves every pod against ONE (policies, view) snapshot —
        the per-pod policy_with_view loop is gone (advisor r1)."""
        cache, mirror, planner = self._build()
        calls = []
        original = mirror.policies_with_view

        def counting(keys):
            calls.append(tuple(keys))
            return original(keys)

        mirror.policies_with_view = counting
        mirror.policy_with_view = None  # any per-pod fallback would crash
        for i in range(3):
            planner.pod_added(
                make_pod(f"p{i}", labels={"telemetry-policy": "plan-pol"})
            )
        assert planner.replan() == 3
        assert len(calls) == 1

    def test_snapshot_is_immune_to_concurrent_metric_delete(self):
        """Mutating the mirror after the snapshot is taken must not change
        what the snapshot resolves to."""
        cache, mirror, planner = self._build()
        policies, view, host_only = mirror.policies_with_view(
            [("default", "plan-pol")]
        )
        compiled = policies[("default", "plan-pol")]
        row_before = compiled.scheduleonmetric_row
        values_before = np.asarray(view.values.lo).copy()
        cache.delete_metric("m")
        cache.write_metric(
            "other", {"n1": NodeMetric(value=Quantity("7"))}
        )
        assert compiled.scheduleonmetric_row == row_before
        assert np.array_equal(np.asarray(view.values.lo), values_before)


class TestSinkhornIneligiblePods:
    def test_ineligible_pod_carries_no_phantom_mass(self):
        """A pod with no eligible node must not add phantom unit mass to
        every column and skew the plan for real pods (advisor r1)."""
        import jax.numpy as jnp

        from platform_aware_scheduling_tpu.ops import i64
        from platform_aware_scheduling_tpu.ops.sinkhorn import (
            sinkhorn_assign_kernel,
        )

        scores = np.array([[30, 20, 10], [30, 20, 10], [5, 5, 5]],
                          dtype=np.int64)
        hi, lo = i64.split_int64_np(scores)
        score = i64.I64(hi=jnp.asarray(hi), lo=jnp.asarray(lo))
        capacity = jnp.asarray(np.array([1, 1, 1], dtype=np.int32))

        eligible_all = jnp.asarray(
            np.array([[1, 1, 1], [1, 1, 1], [0, 0, 0]], dtype=bool)
        )
        with_dead = sinkhorn_assign_kernel(score, eligible_all, capacity)
        # the dead row holds no mass anywhere
        assert float(jnp.sum(with_dead.plan[2])) == pytest.approx(0.0, abs=1e-6)
        assert int(with_dead.assignment.node_for_pod[2]) == -1

        # and the real pods' plan matches the 2-pod problem (no skew)
        two = sinkhorn_assign_kernel(
            i64.I64(hi=jnp.asarray(hi[:2]), lo=jnp.asarray(lo[:2])),
            eligible_all[:2],
            capacity,
        )
        np.testing.assert_allclose(
            np.asarray(with_dead.plan[:2]), np.asarray(two.plan), atol=1e-5
        )
        assert list(np.asarray(with_dead.assignment.node_for_pod[:2])) == list(
            np.asarray(two.assignment.node_for_pod)
        )

"""Closed-loop rebalancer tests (ISSUE 4, docs/rebalance.md).

Hermetic throughout: the synthetic-churn harness from
benchmarks/rebalance_load.py (FakeKubeClient + AutoUpdatingCache +
mirror), with the scheduler's plan-honoring simulated by re-binding
evicted pods onto their planned targets.  Covers the acceptance
criteria: hysteresis semantics, dry-run publishing identical plans with
zero evictions, rate-limit/cooldown/min-available/PDB actuation guards,
and active-vs-label-only convergence.
"""

import json

import pytest

from benchmarks.rebalance_load import ChurnHarness
from platform_aware_scheduling_tpu.extender.server import HTTPRequest, Server
from platform_aware_scheduling_tpu.kube.client import (
    ConflictError,
    NotFoundError,
)
from platform_aware_scheduling_tpu.rebalance import (
    DriftDetector,
    Move,
    SafeActuator,
    TokenBucket,
)
from platform_aware_scheduling_tpu.testing.builders import make_pod
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient


class TestDriftDetector:
    def test_candidate_only_after_k_consecutive_cycles(self):
        drift = DriftDetector(k=3)
        violations = {"node-0": ["pol"]}
        assert drift.observe(violations) == {}  # cycle 1
        assert drift.observe(violations) == {}  # cycle 2
        assert drift.observe(violations) == {"node-0": ["pol"]}  # cycle 3

    def test_recovery_resets_streak(self):
        drift = DriftDetector(k=2)
        violations = {"node-0": ["pol"]}
        assert drift.observe(violations) == {}
        assert drift.observe({}) == {}  # clean cycle: streak reset
        assert drift.observe(violations) == {}  # back to 1, not 2
        assert drift.observe(violations) == {"node-0": ["pol"]}

    def test_streaks_independent_per_node(self):
        drift = DriftDetector(k=2)
        drift.observe({"a": ["p"], "b": ["p"]})
        candidates = drift.observe({"b": ["p"]})
        assert candidates == {"b": ["p"]}
        assert drift.streaks() == {"b": 2}

    def test_k_must_be_positive(self):
        with pytest.raises(ValueError):
            DriftDetector(k=0)


class TestFakeEvictionSubresource:
    def test_success_records_and_deletes(self):
        fake = FakeKubeClient()
        fake.add_pod(make_pod("p1", node_name="node-0", phase="Running"))
        fake.evict_pod("default", "p1")
        assert fake.evictions == [
            {
                "namespace": "default",
                "pod": "p1",
                "node": "node-0",
                "grace_period_seconds": None,
            }
        ]
        with pytest.raises(NotFoundError):
            fake.get_pod("default", "p1")

    def test_denial_is_409_and_keeps_pod(self):
        fake = FakeKubeClient()
        fake.add_pod(make_pod("p1", node_name="node-0", phase="Running"))
        fake.evict_denials.add(("default", "p1"))
        with pytest.raises(ConflictError) as err:
            fake.evict_pod("default", "p1")
        assert err.value.status == 409
        assert fake.evictions == []
        assert fake.get_pod("default", "p1").name == "p1"

    def test_missing_pod_is_404(self):
        with pytest.raises(NotFoundError):
            FakeKubeClient().evict_pod("default", "ghost")


class TestTokenBucket:
    def test_burst_then_refill(self):
        now = [0.0]
        bucket = TokenBucket(rate_per_s=1.0, burst=2, clock=lambda: now[0])
        assert bucket.try_take()
        assert bucket.try_take()
        assert not bucket.try_take()  # burst exhausted
        now[0] = 1.0
        assert bucket.try_take()  # one token refilled
        assert not bucket.try_take()


def _move(name: str, namespace: str = "default") -> Move:
    return Move(
        pod_key=f"{namespace}&{name}",
        namespace=namespace,
        name=name,
        from_node="node-0",
        to_node="node-1",
        gain=1.0,
    )


def _pods(*names, group="g"):
    return [
        make_pod(
            n,
            labels={"pas-workload-group": group},
            node_name="node-0",
            phase="Running",
        )
        for n in names
    ]


class TestSafeActuator:
    def test_dry_run_never_evicts(self):
        fake = FakeKubeClient()
        pods = _pods("p1", "p2")
        for pod in pods:
            fake.add_pod(pod)
        actuator = SafeActuator(fake, mode="dry-run", cooldown_s=0.0)
        result = actuator.actuate(
            [_move("p1"), _move("p2")],
            {f"default&{p.name}": p for p in pods},
            pods,
        )
        assert fake.evictions == []
        assert result.executed == []
        assert result.skip_counts() == {"dry_run": 2}

    def test_rate_limit_bounds_moves_per_cycle(self):
        fake = FakeKubeClient()
        pods = _pods("p1", "p2", "p3", "p4")
        for pod in pods:
            fake.add_pod(pod)
        actuator = SafeActuator(
            fake,
            mode="active",
            rate_per_s=0.0,
            burst=2,
            cooldown_s=0.0,
            clock=lambda: 0.0,
        )
        result = actuator.actuate(
            [_move(p.name) for p in pods],
            {f"default&{p.name}": p for p in pods},
            pods,
        )
        assert len(result.executed) == 2
        assert result.skip_counts() == {"rate_limit": 2}
        assert len(fake.evictions) == 2

    def test_cooldown_blocks_reeviction(self):
        fake = FakeKubeClient()
        now = [0.0]
        actuator = SafeActuator(
            fake,
            mode="active",
            rate_per_s=1000.0,
            burst=10,
            cooldown_s=60.0,
            clock=lambda: now[0],
        )
        pods = _pods("p1", "other")
        for pod in pods:
            fake.add_pod(pod)
        by_key = {f"default&{p.name}": p for p in pods}
        assert actuator.actuate([_move("p1")], by_key, pods).executed
        # the pod comes back (recreated by its controller), violates again
        fake.add_pod(_pods("p1")[0])
        result = actuator.actuate([_move("p1")], by_key, pods)
        assert result.skip_counts() == {"cooldown": 1}
        now[0] = 61.0
        assert actuator.actuate([_move("p1")], by_key, pods).executed

    def test_min_available_guard(self):
        fake = FakeKubeClient()
        lonely = _pods("solo", group="lone")[0]
        fake.add_pod(lonely)
        actuator = SafeActuator(
            fake, mode="active", rate_per_s=1000.0, burst=10, cooldown_s=0.0,
            min_available=1,
        )
        result = actuator.actuate(
            [_move("solo")], {"default&solo": lonely}, [lonely]
        )
        assert result.skip_counts() == {"min_available": 1}
        assert fake.evictions == []
        # a second group member frees the first for eviction
        sibling = _pods("sibling", group="lone")[0]
        fake.add_pod(sibling)
        result = actuator.actuate(
            [_move("solo")],
            {"default&solo": lonely},
            [lonely, sibling],
        )
        assert len(result.executed) == 1

    def test_min_available_counts_same_cycle_evictions(self):
        """Two members of a group planned in ONE cycle: only one may go
        when min_available=1 — the earlier eviction counts against the
        floor for the later move."""
        fake = FakeKubeClient()
        pods = _pods("p1", "p2", group="pair")
        for pod in pods:
            fake.add_pod(pod)
        actuator = SafeActuator(
            fake, mode="active", rate_per_s=1000.0, burst=10, cooldown_s=0.0,
            min_available=1,
        )
        result = actuator.actuate(
            [_move("p1"), _move("p2")],
            {f"default&{p.name}": p for p in pods},
            pods,
        )
        assert len(result.executed) == 1
        assert result.skip_counts() == {"min_available": 1}

    def test_min_available_ignores_terminating_pods(self):
        """A pod with deletionTimestamp set is on its way out and must
        not count as available for the group floor."""
        fake = FakeKubeClient()
        healthy = _pods("healthy", group="pair")[0]
        terminating = _pods("terminating", group="pair")[0]
        terminating.metadata["deletionTimestamp"] = "2026-08-04T00:00:00Z"
        fake.add_pod(healthy)
        fake.add_pod(terminating)
        actuator = SafeActuator(
            fake, mode="active", rate_per_s=1000.0, burst=10, cooldown_s=0.0,
            min_available=1,
        )
        result = actuator.actuate(
            [_move("healthy")],
            {"default&healthy": healthy},
            [healthy, terminating],
        )
        assert result.skip_counts() == {"min_available": 1}
        assert fake.evictions == []

    def test_pdb_409_recorded_not_raised(self):
        fake = FakeKubeClient()
        pods = _pods("p1", "p2")
        for pod in pods:
            fake.add_pod(pod)
        fake.evict_denials.add(("default", "p1"))
        actuator = SafeActuator(
            fake, mode="active", rate_per_s=1000.0, burst=10, cooldown_s=0.0
        )
        result = actuator.actuate(
            [_move("p1"), _move("p2")],
            {f"default&{p.name}": p for p in pods},
            pods,
        )
        assert result.skip_counts() == {"pdb": 1}
        assert [m.name for m in result.executed] == ["p2"]


SMALL = dict(num_nodes=8, hot_nodes=2, pods_per_hot_node=6)


class TestRebalanceLoop:
    def test_hysteresis_delays_candidacy(self):
        harness = ChurnHarness(mode="active", hysteresis_cycles=3, **SMALL)
        first = harness.step()
        second = harness.step()
        third = harness.step()
        assert first["violating_nodes"] and not first["candidate_nodes"]
        assert second["violating_nodes"] and not second["candidate_nodes"]
        assert third["candidate_nodes"] == third["violating_nodes"]
        assert harness.fake.evictions  # actuation started at cycle K

    def test_active_converges_label_only_does_not(self):
        active = ChurnHarness(
            mode="active", hysteresis_cycles=2, max_moves=6, **SMALL
        )
        converged_at = active.run_until_converged(max_cycles=15)
        assert converged_at is not None, "active mode must reach zero violations"
        assert active.fake.evictions

        off = ChurnHarness(
            mode="off", hysteresis_cycles=2, max_moves=6, **SMALL
        )
        assert off.run_until_converged(max_cycles=15) is None
        assert off.fake.evictions == []
        # labels were still applied — the reference-parity half lives on
        labeled = [
            node
            for node in off.fake.list_nodes()
            if node.get_labels().get("rebalance-pol") == "violating"
        ]
        assert labeled

    def test_dry_run_publishes_identical_plans_zero_evictions(self):
        dry = ChurnHarness(mode="dry-run", hysteresis_cycles=2, **SMALL)
        active = ChurnHarness(mode="active", hysteresis_cycles=2, **SMALL)
        dry_record = active_record = None
        for _ in range(2):
            dry_record = dry.step()
            active_record = active.step()
        # cycle K: both planned; the dry-run plan is byte-identical
        assert dry_record["moves"] == active_record["moves"]
        assert dry_record["moves"], "the planning cycle must propose moves"
        assert dry.fake.evictions == []
        assert dry_record["executed"] == []
        assert set(dry_record["skipped"]) == {"dry_run"}
        assert active.fake.evictions
        assert active_record["executed"]

    def test_churn_budget_bounds_moves(self):
        harness = ChurnHarness(
            mode="active", hysteresis_cycles=1, max_moves=2, **SMALL
        )
        for _ in range(3):
            record = harness.step()
            assert len(record["moves"]) <= 2
            assert len(record["executed"]) <= 2

    def test_moves_target_non_violating_nodes(self):
        harness = ChurnHarness(mode="active", hysteresis_cycles=1, **SMALL)
        record = harness.step()
        violating = set(record["violating_nodes"])
        assert record["moves"]
        for move in record["moves"]:
            assert move["from_node"] in violating
            assert move["to_node"] not in violating

    def test_inflow_cap_spreads_moves_and_records_deferrals(self):
        """The herding pin (tests/scenarios/rebalance_herd.json, found
        by the fuzzer): a cycle never lands more than max_inflow=1 move
        on any destination, and the overflow shows up as
        ``deferred_moves`` instead of a stampede onto one cool node."""
        harness = ChurnHarness(
            mode="active",
            hysteresis_cycles=1,
            max_moves=8,
            num_nodes=4,
            hot_nodes=2,
            pods_per_hot_node=6,
        )
        record = harness.step()
        assert record["moves"]
        destinations = [m["to_node"] for m in record["moves"]]
        assert len(destinations) == len(set(destinations))
        # 12 hot pods chasing 2 cool nodes: the cap must bite
        assert record["deferred_moves"] > 0

    def test_violations_published_even_when_labeling_fails(self):
        """A node-patch failure window must not freeze hysteresis
        streaks: the violation map is published every cycle regardless,
        so clean cycles during the window still reset streaks."""
        harness = ChurnHarness(mode="dry-run", hysteresis_cycles=2, **SMALL)
        # publish telemetry once so the hot nodes actually violate: the
        # enforcement pass only patches nodes whose labels can change,
        # so a patch failure needs a real violation to surface
        harness.step()

        def broken_patch(name, payload):
            raise RuntimeError("RBAC says no")

        harness.fake.patch_node = broken_patch
        with pytest.raises(Exception):
            harness.strategy.enforce(harness.enforcer, harness.cache)
        # the failing cycle still reached the rebalancer
        assert harness.rebalancer.status()["cycles"] == 2

    def test_node_list_failure_aborts_cycle(self):
        """Capacity must never be fabricated: if nodes cannot be listed
        the cycle raises (the guarded observer logs it) instead of
        planning against default capacity and evicting."""
        harness = ChurnHarness(mode="active", hysteresis_cycles=1, **SMALL)

        def broken_list_nodes(label_selector=None):
            raise RuntimeError("apiserver down")

        harness.fake.list_nodes = broken_list_nodes
        with pytest.raises(RuntimeError):
            # enforce() itself needs list_nodes; drive the cycle directly
            harness.rebalancer.cycle({"node-0": ["rebalance-pol"]})
        assert harness.fake.evictions == []

    def test_sinkhorn_solver_converges_too(self):
        harness = ChurnHarness(
            mode="active",
            hysteresis_cycles=1,
            max_moves=6,
            solver="sinkhorn",
            num_nodes=8,
            hot_nodes=2,
            pods_per_hot_node=6,
        )
        assert harness.run_until_converged(max_cycles=15) is not None


class TestDebugEndpoint:
    def test_debug_rebalance_serves_status(self):
        harness = ChurnHarness(mode="dry-run", hysteresis_cycles=1, **SMALL)
        harness.step()

        class _Sched:
            def __init__(self, rebalancer):
                self.rebalancer = rebalancer

        server = Server(_Sched(harness.rebalancer))
        response = server.route(
            HTTPRequest(method="GET", path="/debug/rebalance", headers={}, body=b"")
        )
        assert response.status == 200
        body = json.loads(response.body)
        assert body["mode"] == "dry-run"
        assert body["last_plan"]["moves"]
        assert body["cycles"] == 1

    def test_debug_rebalance_404_when_absent(self):
        class _Sched:
            pass

        server = Server(_Sched())
        response = server.route(
            HTTPRequest(method="GET", path="/debug/rebalance", headers={}, body=b"")
        )
        assert response.status == 404

    def test_debug_rebalance_get_only(self):
        class _Sched:
            pass

        server = Server(_Sched())
        response = server.route(
            HTTPRequest(method="POST", path="/debug/rebalance", headers={}, body=b"{}")
        )
        assert response.status == 405


class TestMetrics:
    def test_rebalance_counters_move(self):
        from platform_aware_scheduling_tpu.utils import trace

        def totals():
            return {
                "plans": trace.COUNTERS.get("pas_rebalance_plans_total"),
                "planned": trace.COUNTERS.get(
                    "pas_rebalance_moves_planned_total"
                ),
                "executed": trace.COUNTERS.get(
                    "pas_rebalance_moves_executed_total"
                ),
            }

        before = totals()
        harness = ChurnHarness(mode="active", hysteresis_cycles=1, **SMALL)
        harness.step()
        after = totals()
        assert after["plans"] > before["plans"]
        assert after["planned"] > before["planned"]
        assert after["executed"] > before["executed"]

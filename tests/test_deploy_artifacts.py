"""Deploy artifacts must be renderable and well-formed without a cluster:
the three metrics-pipeline charts (deploy/charts/*), the power demo
(docs/power/), and the raw manifests (deploy/tas, deploy/gas).

Chart templates restrict themselves to simple ``{{ .Values.* }}`` /
``{{ .Release.* }}`` / ``{{ .Chart.Name }}`` substitutions (no
conditionals/loops) precisely so this test can render them the way
``helm template`` would and schema-check the output hermetically.
"""

import os
import re

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHARTS = os.path.join(REPO, "deploy", "charts")
CHART_NAMES = ["node-exporter", "prometheus", "custom-metrics-adapter"]

_SUB = re.compile(r"\{\{\s*([^}]+?)\s*\}\}")


def render(template: str, values: dict, release="rel", namespace="default",
           chart="chart") -> str:
    """The helm-subset renderer: resolves .Values paths, .Release.Name,
    .Release.Namespace, .Chart.Name; anything else is an error."""

    def resolve(match):
        expr = match.group(1).strip()
        if expr == ".Release.Name":
            return release
        if expr == ".Release.Namespace":
            return namespace
        if expr == ".Chart.Name":
            return chart
        if expr.startswith(".Values."):
            node = values
            for part in expr[len(".Values."):].split("."):
                assert isinstance(node, dict) and part in node, (
                    f"unresolved values path {expr}"
                )
                node = node[part]
            assert not isinstance(node, (dict, list)), f"non-scalar {expr}"
            return str(node)
        raise AssertionError(f"template uses unsupported construct: {expr}")

    return _SUB.sub(resolve, template)


def chart_docs(chart_dir: str):
    """All rendered YAML documents of one chart."""
    with open(os.path.join(chart_dir, "values.yaml")) as f:
        values = yaml.safe_load(f)
    tdir = os.path.join(chart_dir, "templates")
    docs = []
    for name in sorted(os.listdir(tdir)):
        with open(os.path.join(tdir, name)) as f:
            rendered = render(f.read(), values)
        assert "{{" not in rendered, f"unrendered expression in {name}"
        for doc in yaml.safe_load_all(rendered):
            if doc is not None:
                docs.append((name, doc))
    return docs


class TestCharts:
    @pytest.mark.parametrize("chart", CHART_NAMES)
    def test_chart_metadata(self, chart):
        with open(os.path.join(CHARTS, chart, "Chart.yaml")) as f:
            meta = yaml.safe_load(f)
        assert meta["apiVersion"] == "v2"
        assert meta["name"] == chart
        assert meta["version"]

    @pytest.mark.parametrize("chart", CHART_NAMES)
    def test_templates_render_to_valid_k8s_objects(self, chart):
        docs = chart_docs(os.path.join(CHARTS, chart))
        assert docs, f"chart {chart} rendered no documents"
        for name, doc in docs:
            assert "kind" in doc and "apiVersion" in doc, (name, doc)
            assert doc["metadata"].get("name"), (name, doc)

    def test_pipeline_wiring(self):
        """The load-bearing cross-references: DaemonSet textfile mount,
        prometheus config name matches its deployment volume, adapter rule
        maps node_* onto Node objects, APIService points at the adapter
        service."""
        ne = dict_by_kind(chart_docs(os.path.join(CHARTS, "node-exporter")))
        ds = ne["DaemonSet"]
        args = ds["spec"]["template"]["spec"]["containers"][0]["args"]
        assert any("--collector.textfile.directory" in a for a in args)

        prom = dict_by_kind(chart_docs(os.path.join(CHARTS, "prometheus")))
        config_name = prom["ConfigMap"]["metadata"]["name"]
        volumes = prom["Deployment"]["spec"]["template"]["spec"]["volumes"]
        assert any(
            v.get("configMap", {}).get("name") == config_name for v in volumes
        )
        prom_yml = yaml.safe_load(prom["ConfigMap"]["data"]["prometheus.yml"])
        jobs = {j["job_name"] for j in prom_yml["scrape_configs"]}
        assert {"kubernetes-nodes", "kubernetes-pods"} <= jobs

        ad = chart_docs(os.path.join(CHARTS, "custom-metrics-adapter"))
        by_kind = dict_by_kind(ad)
        rule_cfg = yaml.safe_load(by_kind["ConfigMap"]["data"]["config.yaml"])
        node_rules = [
            r
            for r in rule_cfg["rules"]
            if r["resources"]["overrides"]["instance"]["resource"] == "node"
        ]
        assert any("node_" in r["seriesQuery"] for r in node_rules)
        assert any(r["name"].get("as") == "power" for r in node_rules)
        # the power HPA consumes `power` as an External metric: the
        # adapter must carry externalRules AND register the
        # external.metrics.k8s.io APIService
        ext_rules = rule_cfg["externalRules"]
        assert any(r["name"].get("as") == "power" for r in ext_rules)
        svc_name = by_kind["Service"]["metadata"]["name"]
        apiservices = [d for n, d in ad if d["kind"] == "APIService"]
        assert {a["metadata"]["name"] for a in apiservices} == {
            "v1beta2.custom.metrics.k8s.io",
            "v1beta1.custom.metrics.k8s.io",
            "v1beta1.external.metrics.k8s.io",
        }
        for a in apiservices:
            assert a["spec"]["service"]["name"] == svc_name
        # node-exporter port coupling: prometheus scrapes the port the
        # node-exporter chart serves on
        with open(
            os.path.join(CHARTS, "node-exporter", "values.yaml")
        ) as f:
            ne_port = yaml.safe_load(f)["port"]
        with open(os.path.join(CHARTS, "prometheus", "values.yaml")) as f:
            assert yaml.safe_load(f)["nodeExporterPort"] == ne_port


def dict_by_kind(docs):
    return {doc["kind"]: doc for _, doc in docs}


def yaml_files_under(*parts):
    root = os.path.join(REPO, *parts)
    found = []
    for dirpath, _, files in os.walk(root):
        for name in files:
            if name.endswith((".yaml", ".yml")):
                found.append(os.path.join(dirpath, name))
    return found


class TestRawManifests:
    @pytest.mark.parametrize(
        "path",
        yaml_files_under("docs", "power")
        + yaml_files_under("deploy", "tas")
        + yaml_files_under("deploy", "gas")
        + yaml_files_under("deploy", "extender-configuration")
        + yaml_files_under("deploy", "health-metric-demo"),
        ids=lambda p: os.path.relpath(p, REPO),
    )
    def test_parses_as_k8s_yaml(self, path):
        with open(path) as f:
            docs = [d for d in yaml.safe_load_all(f) if d is not None]
        assert docs, path
        for doc in docs:
            assert "kind" in doc and "apiVersion" in doc, path
            # component-config kinds (KubeSchedulerConfiguration,
            # DeschedulerPolicy) are files, not cluster objects — no name
            if "metadata" in doc:
                assert doc["metadata"].get("name"), path

    def test_power_demo_complete(self):
        names = {
            os.path.basename(p) for p in yaml_files_under("docs", "power")
        }
        assert {
            "daemonset.yaml",
            "configmap.yaml",
            "service.yaml",
            "tas-policy.yaml",
            "power-hungry-application.yaml",
            "power-autoscaler.yaml",
        } <= names
        assert os.path.exists(
            os.path.join(REPO, "docs", "power", "collectd", "Dockerfile")
        )
        assert os.path.exists(
            os.path.join(REPO, "docs", "power", "collectd", "rapl_reader.py")
        )

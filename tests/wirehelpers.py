"""Shared raw-socket HTTP helpers for the tracing/observability suites:
one place owns the test-side wire framing (request rendering, response
parse, server starters) so a framing change never has to be fixed in
several copies."""

import socket
import threading

from platform_aware_scheduling_tpu.extender.server import Server
from platform_aware_scheduling_tpu.serving import AsyncServer


def start_threaded(ext) -> Server:
    server = Server(ext, metrics_provider=ext.metrics_text)
    threading.Thread(
        target=lambda: server.start_server(
            port="0", unsafe=True, host="127.0.0.1", block=True
        ),
        daemon=True,
    ).start()
    assert server.wait_ready(10)
    return server


def start_async(ext, **kwargs) -> AsyncServer:
    server = AsyncServer(ext, **kwargs)
    server.start_server(port="0", unsafe=True, host="127.0.0.1", block=False)
    assert server.wait_ready(10)
    return server


def post_bytes(path: str, body: bytes, extra: str = "") -> bytes:
    """Rendered POST request bytes (keep-alive, JSON content type)."""
    return (
        f"POST {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Type: application/json\r\n{extra}"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode() + body


def raw_request(port: int, payload: bytes, timeout: float = 15.0):
    """(status, lowercased headers, body) for one request over a fresh
    socket."""
    sock = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    try:
        sock.sendall(payload)
        buf = bytearray()
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("closed before header")
            buf += chunk
        head, _, rest = bytes(buf).partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        status = int(lines[0].split(b" ", 2)[1])
        headers = {}
        length = 0
        for line in lines[1:]:
            name, _, value = line.partition(b":")
            headers[name.decode().lower()] = value.strip().decode()
            if name.lower() == b"content-length":
                length = int(value)
        body = bytearray(rest)
        while len(body) < length:
            chunk = sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("closed mid-body")
            body += chunk
        return status, headers, bytes(body[:length])
    finally:
        sock.close()


def get_request(port: int, path: str):
    payload = (
        f"GET {path} HTTP/1.1\r\nHost: t\r\nConnection: close\r\n\r\n"
    ).encode()
    return raw_request(port, payload)

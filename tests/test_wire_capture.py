"""The wire-capture extraction loop: a --v=5 server log round-trips back
into request/response fixture pairs (tests/golden/from_capture.py), so
the kind-e2e artifact really can refresh the golden fixtures."""

import json
import os
import subprocess
import sys

from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    Server,
)
from platform_aware_scheduling_tpu.utils import klog

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")
sys.path.insert(0, GOLDEN)

import from_capture  # noqa: E402


class _Echo:
    def prioritize(self, request):
        from platform_aware_scheduling_tpu.extender.server import HTTPResponse

        return HTTPResponse.json(b'[{"Host": "n1", "Score": 10}]\n')

    def filter(self, request):
        from platform_aware_scheduling_tpu.extender.server import HTTPResponse

        return HTTPResponse.json(
            b'{"Nodes": null, "NodeNames": ["n1"], "FailedNodes": {}, '
            b'"Error": ""}\n'
        )

    def bind(self, request):
        from platform_aware_scheduling_tpu.extender.server import HTTPResponse

        return HTTPResponse(status=404)


class TestWireCaptureRoundTrip:
    def test_v5_log_extracts_pairs(self, tmp_path, monkeypatch):
        import io
        import logging

        monkeypatch.setattr(klog, "_verbosity", 5, raising=False)
        # capture through klog's own logger: its stream handler binds
        # sys.stderr at first configure (possibly before this test), so
        # capsys can't see it reliably across suite orderings
        sink = io.StringIO()
        handler = logging.StreamHandler(sink)
        handler.setFormatter(logging.Formatter("%(message)s"))
        klog._logger.addHandler(handler)
        try:
            server = Server(_Echo())
            body = (
                b'{"pod": {"metadata": {"name": "p"}}, "nodenames": ["n1"]}'
            )
            for path in ("/scheduler/prioritize", "/scheduler/filter"):
                server.route(
                    HTTPRequest(
                        method="POST",
                        path=path,
                        headers={"Content-Type": "application/json"},
                        body=body,
                    )
                )
        finally:
            klog._logger.removeHandler(handler)
        log_text = sink.getvalue()
        assert "WIRE request" in log_text and "WIRE response" in log_text

        log = tmp_path / "tas.log"
        log.write_text(log_text)
        out = tmp_path / "pairs"
        rc = from_capture.main(str(log), str(out))
        assert rc == 0
        index = json.loads((out / "index.json").read_text())
        verbs = [e["verb"] for e in index]
        assert verbs == ["prioritize", "filter"]
        body = (
            b'{"pod": {"metadata": {"name": "p"}}, "nodenames": ["n1"]}'
        )
        expected_resp = {
            "prioritize": b'[{"Host": "n1", "Score": 10}]\n',
            "filter": (
                b'{"Nodes": null, "NodeNames": ["n1"], "FailedNodes": {}, '
                b'"Error": ""}\n'
            ),
        }
        for entry in index:
            # byte-exact round trip, including the trailing newline the
            # encoders emit (base64 transport can't lose or split it)
            assert (out / entry["request"]).read_bytes() == body
            assert entry["candidates"] == 1
            assert entry["status"] == 200
            resp = (out / entry["response"]).read_bytes()
            assert resp == expected_resp[entry["verb"]]

    def test_truncated_response_consumes_its_request(self):
        """A corrupt response line must eat its request too — otherwise
        every later pair for that verb shifts by one and fixtures get
        committed with request N paired to response N+1."""
        import base64

        def b64(b):
            return base64.b64encode(b).decode()

        log = "\n".join(
            [
                f"I WIRE request POST /scheduler/prioritize len=5 b64={b64(b'req-1')}",
                # truncated at a 4-char base64 boundary: decodes "validly"
                # but the declared length exposes it
                f"I WIRE response /scheduler/prioritize status=200 len=9 b64={b64(b'resp')[:4]}",
                f"I WIRE request POST /scheduler/prioritize len=5 b64={b64(b'req-2')}",
                f"I WIRE response /scheduler/prioritize status=200 len=6 b64={b64(b'resp-2')}",
            ]
        )
        pairs = list(from_capture.extract(log))
        assert pairs == [("prioritize", b"req-2", 200, b"resp-2")]

    def test_truncated_request_discards_its_response(self):
        import base64

        def b64(b):
            return base64.b64encode(b).decode()

        log = "\n".join(
            [
                # request line cut mid-base64 (declared length mismatch)
                f"I WIRE request POST /scheduler/filter len=100 b64={b64(b'cut!')}",
                f"I WIRE response /scheduler/filter status=200 len=7 b64={b64(b'resp-X!')}",
                f"I WIRE request POST /scheduler/filter len=5 b64={b64(b'req-2')}",
                f"I WIRE response /scheduler/filter status=200 len=6 b64={b64(b'resp-2')}",
            ]
        )
        pairs = list(from_capture.extract(log))
        assert pairs == [("filter", b"req-2", 200, b"resp-2")]

    def test_cli_usage(self):
        proc = subprocess.run(
            [sys.executable, os.path.join(GOLDEN, "from_capture.py")],
            capture_output=True,
            text=True,
        )
        assert proc.returncode == 2

"""Partition plane (docs/sharding.md):

  * partition math — consistent-hash determinism, the request-path
    memo, rendezvous ownership with minimal churn;
  * journaled/fenced ownership — first-tick assignment, convergence of
    concurrent coordinators, dead-owner handoff with epoch bumps +
    event-spine provenance, heartbeat renewal cadence, the lost-write
    race (serve what you READ, retry next tick), the leadership gate,
    and the static-owner bench mode;
  * digests — build from a seeded mirror (violators, both-ends top-k,
    universe digest), lossless wire round trip, fenced ingest,
    edge-triggered staleness, and the has_violations fastpath gate's
    deliberately conservative edges;
  * scatter/gather serving — review_filter's remote-violator merge and
    fail-open accounting, gather_metric's local+digest merge,
    remote_holds_possible routing, straddling-gang anchor resolution,
    and the extender-level Filter/Prioritize integration;
  * wire — /debug/shard indexed, 404 unwired, 405 non-GET, 200 payload
    on BOTH front-ends; off path (--shard=off, the default) constructs
    nothing, exports no pas_shard_* families, and serves byte-identical
    responses; an all-owning plane changes no Filter byte either;
  * trace — every pas_shard_* family the plane emits is declared;
  * HA harness — a partitioned fleet covers the world exactly once and
    a killed owner's partitions move to survivors.
"""

import json

import pytest

from benchmarks.http_load import _policy_obj, build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import (
    DEBUG_ENDPOINTS,
    HTTPRequest,
)
from platform_aware_scheduling_tpu.kube.retry import stable_hash
from platform_aware_scheduling_tpu.shard import ShardPlane
from platform_aware_scheduling_tpu.shard.digest import (
    DIGEST_FORMAT,
    DigestStore,
    PartitionDigest,
    ShardGossip,
    build_partition_digests,
    universe_digest,
)
from platform_aware_scheduling_tpu.shard.partition import (
    OWNERS_FORMAT,
    HandoffCoordinator,
    PartitionMap,
    rendezvous_owner,
)
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.testing.faults import (
    FakeClock as FaultsFakeClock,
    FaultPlan,
)
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.events import JOURNAL
from platform_aware_scheduling_tpu.utils.quantity import Quantity
from wirehelpers import (
    get_request,
    post_bytes,
    raw_request,
    start_async,
    start_threaded,
)


@pytest.fixture(autouse=True)
def _clean_journal():
    JOURNAL.reset()
    yield
    JOURNAL.reset()


def verb_request(path, body):
    return HTTPRequest(
        method="POST",
        path=path,
        headers={"Content-Type": "application/json"},
        body=body,
    )


def journal_events(event):
    return [r for r in JOURNAL.snapshot() if r["event"] == event]


def static_plane(identity="r0", partitions=4, owners=None, **kw):
    """A plane in bench mode: fixed ownership, no kube I/O."""
    if owners is None:
        owners = {p: identity for p in range(partitions)}
    return ShardPlane(
        identity, partitions, kube_client=None, static_owners=owners, **kw
    )


class TestPartitionMap:
    def test_partition_of_is_the_stable_hash_mod_p(self):
        pmap = PartitionMap(4)
        for name in ("node-0", "node-1", "tpu-worker-99"):
            assert pmap.partition_of(name) == stable_hash(name) % 4
            # second lookup serves from the memo and must agree
            assert pmap.partition_of(name) == stable_hash(name) % 4
            assert pmap._memo[name] == stable_hash(name) % 4

    def test_group_partitions_every_name_and_preserves_order(self):
        pmap = PartitionMap(3)
        names = [f"node-{i:03d}" for i in range(60)]
        groups = pmap.group(names)
        regrouped = [n for p in sorted(groups) for n in groups[p]]
        assert sorted(regrouped) == sorted(names)
        for p, members in groups.items():
            assert members == [n for n in names if pmap.partition_of(n) == p]
            assert pmap.nodes_in(names, p) == members

    def test_group_serves_from_the_memo(self):
        """The request path must probe the memo, not rehash: poisoning
        a memo entry visibly redirects group()."""
        pmap = PartitionMap(4)
        pmap.partition_of("node-x")
        honest = pmap._memo["node-x"]
        pmap._memo["node-x"] = (honest + 1) % 4
        assert pmap.group(["node-x"]) == {(honest + 1) % 4: ["node-x"]}

    def test_single_partition_and_validation(self):
        pmap = PartitionMap(1)
        assert pmap.group(["a", "b"]) == {0: ["a", "b"]}
        with pytest.raises(ValueError):
            PartitionMap(0)


class TestRendezvous:
    MEMBERS = ["replica-a", "replica-b", "replica-c", "replica-d"]

    def test_deterministic_and_order_independent(self):
        for p in range(8):
            winner = rendezvous_owner(p, self.MEMBERS)
            assert winner in self.MEMBERS
            assert winner == rendezvous_owner(p, list(reversed(self.MEMBERS)))

    def test_minimal_churn_on_member_departure(self):
        """Removing one member moves ONLY the partitions it owned —
        every other partition keeps its winner (the rendezvous
        property that makes handoff cheap)."""
        before = {p: rendezvous_owner(p, self.MEMBERS) for p in range(32)}
        gone = "replica-b"
        survivors = [m for m in self.MEMBERS if m != gone]
        after = {p: rendezvous_owner(p, survivors) for p in range(32)}
        for p in range(32):
            if before[p] != gone:
                assert after[p] == before[p], f"partition {p} moved"
            else:
                assert after[p] in survivors

    def test_empty_membership(self):
        assert rendezvous_owner(0, []) is None


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make_coordinator(client, identity, clock, partitions=4, ttl=15.0, **kw):
    return HandoffCoordinator(
        client, identity=identity, partitions=partitions,
        member_ttl_s=ttl, clock=clock, **kw,
    )


class TestHandoffCoordinator:
    def test_first_tick_journals_and_assigns_everything(self):
        client, clock = FakeKubeClient(), FakeClock()
        coord = make_coordinator(client, "replica-a", clock)
        coord.tick()
        assert coord.owned() == frozenset(range(4))
        assert all(coord.epoch(p) == 1 for p in range(4))
        # the journal is durable, schema-stamped state
        cm = client.get_configmap("default", "pas-shard-partitions")
        state = json.loads(cm["data"]["state"])
        assert state["format"] == OWNERS_FORMAT
        assert set(state["owners"]) == {"0", "1", "2", "3"}
        # cold assignment publishes partition_assign, never handoff
        assert len(journal_events("partition_assign")) == 4
        assert journal_events("partition_handoff") == []
        assert coord.handoffs() == 0

    def test_concurrent_coordinators_converge(self):
        client, clock = FakeKubeClient(), FakeClock()
        a = make_coordinator(client, "replica-a", clock)
        b = make_coordinator(client, "replica-b", clock)
        a.tick()
        b.tick()
        a.tick()  # a re-reads the journal that now includes b
        expected = {
            p: rendezvous_owner(p, ["replica-a", "replica-b"])
            for p in range(4)
        }
        for p in range(4):
            assert a.owner(p) == b.owner(p) == expected[p]
        assert a.owned() | b.owned() == frozenset(range(4))
        assert a.owned() & b.owned() == frozenset()

    def test_dead_owner_hands_off_with_epoch_bump(self):
        client, clock = FakeKubeClient(), FakeClock()
        a = make_coordinator(client, "replica-a", clock, ttl=10.0)
        b = make_coordinator(client, "replica-b", clock, ttl=10.0)
        a.tick()
        b.tick()
        a.tick()
        lost = sorted(b.owned())
        assert lost, "rendezvous should give replica-b something at P=4"
        epochs_before = {p: a.epoch(p) for p in lost}
        JOURNAL.reset()
        # b never heartbeats again; past the TTL its partitions move
        clock.t = 11.0
        a.tick()
        assert a.owned() == frozenset(range(4))
        for p in lost:
            assert a.epoch(p) == epochs_before[p] + 1
        handoffs = journal_events("partition_handoff")
        assert {e["data"]["partition"] for e in handoffs} == set(lost)
        for e in handoffs:
            assert e["data"]["from"] == "replica-b"
            assert e["data"]["to"] == "replica-a"
        assert a.handoffs() == len(lost)

    def test_heartbeat_renews_at_a_third_of_the_ttl(self):
        client, clock = FakeKubeClient(), FakeClock()
        coord = make_coordinator(client, "replica-a", clock, ttl=15.0)
        coord.tick()
        rv0 = client.get_configmap("default", "pas-shard-partitions")[
            "metadata"
        ]["resourceVersion"]
        clock.t = 2.0  # inside TTL/3: a quiet tick must not write
        coord.tick()
        rv1 = client.get_configmap("default", "pas-shard-partitions")[
            "metadata"
        ]["resourceVersion"]
        assert rv1 == rv0
        clock.t = 6.0  # past TTL/3: the stamp must renew
        coord.tick()
        cm = client.get_configmap("default", "pas-shard-partitions")
        assert cm["metadata"]["resourceVersion"] != rv0
        state = json.loads(cm["data"]["state"])
        assert state["members"]["replica-a"] == 6.0

    def test_lost_write_race_serves_what_was_read(self):
        """A failed journal write must leave the coordinator serving
        the journaled assignment it READ — no phantom local handoffs,
        no events — and succeed on the next tick."""
        client, clock = FakeKubeClient(), FakeClock()
        a = make_coordinator(client, "replica-a", clock)
        a.tick()
        b = make_coordinator(client, "replica-b", clock)
        real_update = client.update_configmap

        def failing_update(cm):
            raise RuntimeError("409 conflict: resourceVersion mismatch")

        client.update_configmap = failing_update
        JOURNAL.reset()
        b.tick()
        # b computed a reassignment but could not journal it: it must
        # keep serving the read state (everything owned by replica-a)
        assert b.owned() == frozenset()
        assert all(b.owner(p) == "replica-a" for p in range(4))
        assert b.handoffs() == 0
        assert journal_events("partition_handoff") == []
        assert journal_events("partition_assign") == []
        client.update_configmap = real_update
        clock.t = 6.0
        b.tick()
        assert b.owned(), "retry against the fresh journal must land"

    def test_follower_never_reassigns(self):
        class Leadership:
            def __init__(self, leader):
                self.leader = leader

            def is_leader(self):
                return self.leader

        client, clock = FakeKubeClient(), FakeClock()
        follower = make_coordinator(
            client, "replica-a", clock, leadership=Leadership(False)
        )
        follower.tick()
        assert follower.owned() == frozenset()
        # its heartbeat still lands, so a leader sees it as live
        leader = make_coordinator(
            client, "replica-b", clock, leadership=Leadership(True)
        )
        leader.tick()
        owners = {leader.owner(p) for p in range(4)}
        assert owners <= {"replica-a", "replica-b"}
        assert "replica-a" in json.loads(
            client.get_configmap("default", "pas-shard-partitions")["data"][
                "state"
            ]
        )["members"]

    def test_static_owners_mode_touches_no_journal(self):
        coord = HandoffCoordinator(
            None, identity="owner-1", partitions=3,
            static_owners={0: "owner-0", 1: "owner-1", 2: "owner-2"},
        )
        coord.tick()  # must not raise despite kube_client=None
        assert coord.owned() == frozenset({1})
        assert coord.owner(2) == "owner-2"
        assert all(coord.epoch(p) == 1 for p in range(3))


def seeded_extender(num_nodes=24):
    ext, names = build_extender(num_nodes, device=True)
    return ext, names


def make_digest(partition, epoch=1, stamp=0.0, violations=None, topk=None,
                owner="remote"):
    return PartitionDigest(
        partition=partition,
        owner=owner,
        epoch=epoch,
        version=1,
        stamp=stamp,
        node_count=1,
        universe=7,
        topk=topk or {},
        violations=violations or {},
    )


class TestDigestBuild:
    def test_build_summarizes_owned_partitions_only(self):
        ext, names = seeded_extender()
        pmap = PartitionMap(4)
        groups = pmap.group(names)
        owned = frozenset({0, 2})
        # push two partition-0 nodes over the dontschedule target
        # (write_metric replaces the whole per-node map, so re-seed
        # every node and boost just the violators)
        violators = sorted(groups[0][:2])
        ext.cache.write_metric(
            "load_metric",
            {
                n: NodeMetric(
                    value=Quantity(2 * 10**9 if n in violators else i + 1)
                )
                for i, n in enumerate(names)
            },
        )
        digests = build_partition_digests(
            ext.mirror, pmap, owned, identity="replica-a",
            epoch_of=lambda p: 5, topk_of=lambda p: 3, clock=lambda: 42.0,
        )
        assert [d.partition for d in digests] == [0, 2]
        for d in digests:
            assert d.owner == "replica-a"
            assert d.epoch == 5
            assert d.stamp == 42.0
            assert d.node_count == len(groups[d.partition])
            assert d.universe == universe_digest(groups[d.partition])
            summary = d.topk["load_metric"]
            # both ends, capped at 2k entries, nodes of this partition
            assert len(summary) <= 6
            assert set(summary) <= set(groups[d.partition])
        by_partition = {d.partition: d for d in digests}
        assert sorted(
            by_partition[0].violations["load-pol"]
        ) == violators
        # partition 2 has no violators: the empty set is OMITTED, so
        # has_violations stays a cheap truthiness walk
        assert by_partition[2].violations == {}
        # the violators also top the high end of the top-k summary
        summary = by_partition[0].topk["load_metric"]
        for v in violators:
            assert summary[v] == max(summary.values())

    def test_wire_round_trip_is_lossless(self):
        digest = make_digest(
            3, epoch=7, stamp=1.5,
            violations={"load-pol": ["node-a", "node-b"]},
            topk={"load_metric": {"node-a": 11, "node-b": -2}},
        )
        obj = json.loads(json.dumps(digest.to_obj()))
        back = PartitionDigest.from_obj(obj)
        assert back.to_obj() == digest.to_obj()
        assert obj["format"] == DIGEST_FORMAT
        assert PartitionDigest.from_obj({"format": "bogus/9"}) is None


class TestDigestStore:
    def make_store(self, epoch=1, stale=10.0):
        clock = FakeClock()
        epochs = {"value": epoch}
        store = DigestStore(
            epoch_of=lambda p: epochs["value"],
            stale_after_s=stale,
            clock=clock,
        )
        return store, clock, epochs

    def test_fenced_ingest_rejected_and_published(self):
        store, _clock, _epochs = self.make_store(epoch=3)
        assert store.put(make_digest(1, epoch=2)) is False
        assert store.fenced_rejects == 1
        (event,) = journal_events("digest_fenced")
        assert event["data"] == {
            "partition": 1, "owner": "remote", "epoch": 2,
            "current_epoch": 3,
        }
        assert store.fresh(1) is None
        # current-epoch digests land
        assert store.put(make_digest(1, epoch=3)) is True
        assert store.fresh(1).epoch == 3

    def test_never_replace_newer_with_older(self):
        store, _clock, _epochs = self.make_store()
        assert store.put(make_digest(0, epoch=1, stamp=5.0)) is True
        assert store.put(make_digest(0, epoch=1, stamp=2.0)) is False
        assert store.fresh(0).stamp == 5.0

    def test_staleness_fails_open_edge_triggered(self):
        store, clock, _epochs = self.make_store(stale=10.0)
        store.put(make_digest(2, stamp=0.0))
        clock.t = 5.0
        assert store.fresh(2) is not None
        clock.t = 10.5
        assert store.fresh(2) is None
        assert store.fresh(2) is None  # second trip, same episode
        assert len(journal_events("digest_stale")) == 1
        # a fresh digest ends the episode; the NEXT one is a new event
        store.put(make_digest(2, stamp=11.0))
        assert store.fresh(2) is not None
        clock.t = 30.0
        assert store.fresh(2) is None
        assert len(journal_events("digest_stale")) == 2

    def test_fenced_since_ingest_fails_open(self):
        store, _clock, epochs = self.make_store(epoch=1)
        store.put(make_digest(0, epoch=1))
        epochs["value"] = 2  # handoff mid-shelf-life
        assert store.fresh(0) is None

    def test_has_violations_is_deliberately_conservative(self):
        store, clock, epochs = self.make_store(stale=10.0)
        assert store.has_violations() is False
        store.put(make_digest(0, violations={"pol": ["n1"]}))
        store.put(make_digest(1))
        assert store.has_violations() is True
        # the gate excludes owned partitions: their violators are the
        # local solve's own facts
        assert store.has_violations(exclude={0}) is False
        # stale and fenced-since-ingest digests KEEP the gate True —
        # the only safe direction is toward the reviewed path
        clock.t = 99.0
        assert store.fresh(0) is None
        assert store.has_violations() is True
        epochs["value"] = 7
        assert store.has_violations() is True


class TestGossip:
    def test_callable_peers_and_dead_peer_accounting(self):
        store, _clock, _epochs = TestDigestStore().make_store()
        payload = {
            "digests": {
                "1": make_digest(1, violations={"pol": ["n"]}).to_obj()
            }
        }

        def dead_peer():
            raise OSError("connection refused")

        gossip = ShardGossip(
            store, peers=[lambda: payload, dead_peer, lambda: b"{}"]
        )
        assert gossip.pull() == 1
        assert gossip.pulls_ok == 2
        assert gossip.pulls_failed == 1
        assert store.fresh(1) is not None
        # once a FRESHER digest is shelved, re-offering the old one
        # ingests nothing (the store's newer-wins rule)
        store.put(make_digest(1, stamp=5.0))
        assert gossip.pull() == 0


class TestGossipFaults:
    """Gossip rides the FaultPlan like every other verb
    (ShardGossip.FAULT_VERB, one consult per peer per round): outages
    and error rates make failed pulls, latency ages what the slow peer
    delivers, a truncated payload merges its surviving prefix — every
    mode fails open, never raises."""

    def _store(self, stale=10.0):
        clock = FaultsFakeClock(start=0.0)
        store = DigestStore(
            epoch_of=lambda p: 1, stale_after_s=stale, clock=clock
        )
        return store, clock

    def _peer(self, partitions, stamp=0.0):
        payload = {
            "digests": {
                str(p): make_digest(p, stamp=stamp).to_obj()
                for p in partitions
            }
        }
        return lambda: payload

    def test_outage_fails_every_pull_until_cleared(self):
        store, _clock = self._store()
        plan = FaultPlan().outage(ShardGossip.FAULT_VERB)
        gossip = ShardGossip(
            store,
            peers=[self._peer([0]), self._peer([1])],
            fault_plan=plan,
        )
        assert gossip.pull() == 0
        assert gossip.pulls_failed == 2 and gossip.pulls_ok == 0
        assert store.fresh(0) is None and store.fresh(1) is None
        plan.clear(ShardGossip.FAULT_VERB)
        assert gossip.pull() == 2
        assert gossip.pulls_ok == 2

    def test_error_rate_is_deterministic_per_peer_slot(self):
        outcomes = []
        for _ in range(2):
            store, _clock = self._store()
            plan = FaultPlan(seed=3).error_rate(
                ShardGossip.FAULT_VERB, 0.5
            )
            gossip = ShardGossip(
                store, peers=[self._peer([p]) for p in range(4)],
                fault_plan=plan,
            )
            rounds = [gossip.pull() for _ in range(4)]
            outcomes.append((rounds, gossip.pulls_ok, gossip.pulls_failed))
        assert outcomes[0] == outcomes[1]
        _rounds, ok, failed = outcomes[0]
        assert ok + failed == 16
        assert 0 < failed < 16  # the rate really fired, and not always

    def test_truncate_merges_the_surviving_prefix(self):
        store, _clock = self._store()
        plan = FaultPlan().truncate(ShardGossip.FAULT_VERB, 1, keep=2)
        gossip = ShardGossip(
            store, peers=[self._peer([3, 1, 0, 2])], fault_plan=plan
        )
        # the cut is deterministic: partition order, first ``keep``
        assert gossip.pull() == 2
        assert gossip.pulls_ok == 1 and gossip.pulls_failed == 0
        assert store.fresh(0) is not None and store.fresh(1) is not None
        assert store.fresh(2) is None and store.fresh(3) is None
        # script exhausted: the next round delivers the full payload
        # (equal-stamp digests re-shelve — newer-wins rejects only
        # strictly older — so all four count as ingested)
        assert gossip.pull() == 4
        assert store.fresh(2) is not None and store.fresh(3) is not None

    def test_latency_fault_ages_what_the_slow_peer_delivers(self):
        store, clock = self._store(stale=10.0)
        plan = FaultPlan().latency(ShardGossip.FAULT_VERB, 1, 30.0)
        gossip = ShardGossip(
            store,
            peers=[self._peer([0], stamp=clock.now())],
            fault_plan=plan,
            fault_clock=clock,
        )
        # the pull succeeds — but the clock advanced past the staleness
        # bound before the payload landed, so serving fails open
        assert gossip.pull() == 1
        assert gossip.pulls_ok == 1
        assert store.fresh(0) is None
        (event,) = journal_events("digest_stale")
        assert event["data"]["partition"] == 0


class TestShardPlane:
    def test_review_filter_merges_remote_violators(self):
        plane = static_plane("r0", 4, owners={0: "r0", 1: "r1", 2: "r2",
                                              3: "r3"})
        names = [f"node-{i:04d}" for i in range(40)]
        remote = [n for n in names if plane.pmap.partition_of(n) == 1]
        stamp = plane.clock()
        plane.store.put(make_digest(
            1, stamp=stamp, violations={"load-pol": [remote[0], "absent-n"]}
        ))
        plane.store.put(make_digest(2, stamp=stamp))
        held, consulted = plane.review_filter("load-pol", names)
        # only violators IN the request are held; partition 3 had no
        # digest so the review failed open for it, visibly
        assert held == [remote[0]]
        assert consulted == 2
        assert plane.gather_local_only == 1
        # a policy the digests never mention holds nothing
        held, consulted = plane.review_filter("other-pol", names)
        assert held == []
        assert consulted == 2

    def test_review_filter_skips_owned_partitions(self):
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r0"})
        plane.store.put(make_digest(
            0, stamp=plane.clock(), violations={"load-pol": ["node-x"]}
        ))
        held, consulted = plane.review_filter("load-pol", ["node-x"])
        assert held == [] and consulted == 0
        assert plane.gather_local_only == 0

    def test_remote_holds_possible_routes_the_fastpath(self):
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r1"})
        assert plane.remote_holds_possible() is False
        # an OWN-partition digest with violators never flips the gate
        plane.store.put(make_digest(
            0, stamp=plane.clock(), violations={"pol": ["mine"]}
        ))
        assert plane.remote_holds_possible() is False
        plane.store.put(make_digest(
            1, stamp=plane.clock(), violations={"pol": ["theirs"]}
        ))
        assert plane.remote_holds_possible() is True

    def test_gather_metric_merges_local_and_digest_values(self):
        ext, names = seeded_extender()
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r1"})
        plane.attach(ext.cache, ext.mirror)
        groups = plane.pmap.group(names)
        local, remote = groups[0], groups[1]
        plane.store.put(make_digest(
            1, stamp=plane.clock(),
            topk={"load_metric": {remote[0]: 123456}},
        ))
        merged = plane.gather_metric("load_metric", names)
        view = ext.mirror.device_view()
        row = view.metric_index["load_metric"]
        for name in local:
            assert merged[name] == int(
                view.values_milli[row, view.node_index[name]]
            )
        assert merged[remote[0]] == 123456
        # remote nodes outside the top-k are absent, like missing
        # metric data on the host path — and the miss is not a
        # local-only event (the digest WAS consulted)
        for name in remote[1:]:
            assert name not in merged
        assert plane.gather_local_only == 0

    def test_gather_metric_counts_missing_remote_digest(self):
        ext, names = seeded_extender()
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r1"})
        plane.attach(ext.cache, ext.mirror)
        plane.gather_metric("load_metric", names)
        assert plane.gather_local_only == 1
        assert plane.counters.get(
            "pas_shard_gather_local_only_total",
            kind="counter",
            labels={"verb": "prioritize"},
        ) == 1

    def test_anchor_partition_resolution(self):
        plane = static_plane("r0", 4, owners={0: "r0", 1: "r1", 2: "r0",
                                              3: "r1"})
        names = [f"node-{i}" for i in range(12)]
        anchored = plane.anchor_partition(names)
        assert anchored == plane.pmap.partition_of(names[0])
        assert plane.owns_anchor(names) == (
            anchored in plane.coordinator.owned()
        )
        # an empty slice anchors nowhere and is always "ours" (the
        # overlay then applies as in full-world mode)
        assert plane.anchor_partition([]) is None
        assert plane.owns_anchor([]) is True

    def test_refresh_filter_cuts_ingest_to_owned(self):
        ext, names = seeded_extender()
        plane = static_plane("r0", 4, owners={0: "r0", 1: "r1", 2: "r2",
                                              3: "r3"})
        plane.attach(ext.cache, ext.mirror)
        info = {n: object() for n in names}
        kept = ext.cache.refresh_filter(info)
        owned_names = plane.pmap.nodes_in(names, 0)
        assert sorted(kept) == sorted(owned_names)
        counters = plane.counters
        assert counters.get(
            "pas_shard_refresh_nodes_total", kind="counter",
            labels={"scope": "owned"},
        ) == len(owned_names)
        assert counters.get(
            "pas_shard_refresh_nodes_total", kind="counter",
            labels={"scope": "skipped"},
        ) == len(names) - len(owned_names)

    def test_refresh_pass_publishes_own_digests(self):
        ext, _names = seeded_extender()
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r0"})
        plane.attach(ext.cache, ext.mirror)
        plane.on_refresh_pass()
        assert set(plane.store.snapshot()["digests"]) == {"0", "1"}
        assert plane.counters.get(
            "pas_shard_ticks_total", kind="counter"
        ) == 1


def find_remote_node(plane, names, partition):
    for name in names:
        if plane.pmap.partition_of(name) == partition:
            return name
    raise AssertionError(f"no node hashed into partition {partition}")


class TestServingIntegration:
    def test_filter_holds_remote_digest_violators(self):
        ext, names = seeded_extender()
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r1"})
        plane.attach(ext.cache, ext.mirror)
        ext.shard = plane
        victim = find_remote_node(plane, names, 1)
        plane.store.put(make_digest(
            1, stamp=plane.clock(), owner="r1",
            violations={"load-pol": [victim]},
        ))
        body = make_bodies(names, "nodenames", count=1)[0]
        response = ext.filter(verb_request("/scheduler/filter", body))
        assert response.status == 200
        out = json.loads(response.body)
        assert victim not in out["NodeNames"]
        assert "remote partition digest" in out["FailedNodes"][victim]
        assert plane.counters.get(
            "pas_shard_gather_held_total", kind="counter"
        ) == 1

    def test_filter_without_remote_violators_matches_full_world(self):
        """The fastpath gate: while no remote digest lists a violator
        the sharded Filter verdict — served natively — is byte-equal to
        the full-world build's."""
        ext_off, names = seeded_extender()
        body = make_bodies(names, "nodenames", count=1)[0]
        baseline = ext_off.filter(verb_request("/scheduler/filter", body))
        ext_on, _names = seeded_extender()
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r1"})
        plane.attach(ext_on.cache, ext_on.mirror)
        ext_on.shard = plane
        plane.store.put(make_digest(1, stamp=plane.clock(), owner="r1"))
        sharded = ext_on.filter(verb_request("/scheduler/filter", body))
        assert sharded.status == baseline.status == 200
        assert sharded.body == baseline.body

    def test_shard_prioritize_ranks_the_merged_map(self):
        ext, names = seeded_extender()
        plane = static_plane("r0", 2, owners={0: "r0", 1: "r1"})
        plane.attach(ext.cache, ext.mirror)
        ext.shard = plane
        remote = find_remote_node(plane, names, 1)
        plane.store.put(make_digest(
            1, stamp=plane.clock(), owner="r1",
            topk={"load_metric": {remote: 10**10}},
        ))
        body = make_bodies(names, "nodenames", count=1)[0]
        response = ext.prioritize(verb_request("/scheduler/prioritize", body))
        assert response.status == 200
        ranked = json.loads(response.body)
        # GreaterThan: the digest's huge value must rank first even
        # though the node lives on a partition this replica never held
        assert ranked[0]["Host"] == remote
        by_host = {r["Host"]: r["Score"] for r in ranked}
        assert max(by_host.values()) == by_host[remote]


@pytest.mark.parametrize("front_end", ["threaded", "async"])
class TestDebugShardEndpoint:
    def _start(self, front_end, ext):
        return start_async(ext) if front_end == "async" else start_threaded(
            ext
        )

    def test_404_when_off(self, front_end):
        ext, _names = seeded_extender(8)
        server = self._start(front_end, ext)
        try:
            status, _, body = get_request(server.port, "/debug/shard")
            assert status == 404
            assert "shard plane" in json.loads(body)["error"]
            status, _, body = get_request(server.port, "/metrics")
            assert status == 200
            assert b"pas_shard_" not in body
        finally:
            server.shutdown()

    def test_payload_and_405(self, front_end):
        ext, _names = seeded_extender(8)
        plane = static_plane("wire-replica", 2,
                             owners={0: "wire-replica", 1: "wire-replica"})
        plane.attach(ext.cache, ext.mirror)
        ext.shard = plane
        plane.on_refresh_pass()
        server = self._start(front_end, ext)
        try:
            status, headers, payload = get_request(
                server.port, "/debug/shard"
            )
            assert status == 200
            assert headers["content-type"] == "application/json"
            out = json.loads(payload)
            assert out["identity"] == "wire-replica"
            assert out["partitions"] == 2
            assert out["coordinator"]["owned"] == [0, 1]
            assert set(out["digests"]) == {"0", "1"}
            for digest in out["digests"].values():
                assert digest["format"] == DIGEST_FORMAT
                assert "age_s" in digest
            assert "gossip" in out and "topk" in out
            # the payload IS the gossip wire format: a peer ingests it
            store, _c, _e = TestDigestStore().make_store()
            assert ShardGossip(store, peers=[lambda: payload]).pull() == 2
            status, _, _ = raw_request(
                server.port, post_bytes("/debug/shard", b"{}")
            )
            assert status == 405
            # the wired plane's families reach the SERVED /metrics on
            # this front-end (the async server aggregates counter sets
            # dynamically — serving/http.py must include the shard set)
            status, _, body = get_request(server.port, "/metrics")
            assert status == 200
            assert b"pas_shard_ticks_total" in body
        finally:
            server.shutdown()

    def test_indexed(self, front_end):
        assert "/debug/shard" in {e["path"] for e in DEBUG_ENDPOINTS}


class TestOffPath:
    def test_default_constructs_nothing(self):
        ext, _names = seeded_extender(8)
        assert ext.shard is None

    @pytest.mark.parametrize("front_end", ["threaded", "async"])
    def test_off_path_wire_byte_identical_and_no_families(self, front_end):
        """Two independent --shard=off builds answer byte-identically
        over real sockets (modulo X-Request-ID) and expose no
        pas_shard_* family at all; an all-owning plane doesn't change
        the Filter bytes either (the gate keeps it on the native
        path)."""
        wire = {}
        for label in ("off_a", "off_b", "on"):
            ext, names = seeded_extender(12)
            if label == "on":
                plane = static_plane("solo", 2,
                                     owners={0: "solo", 1: "solo"})
                plane.attach(ext.cache, ext.mirror)
                ext.shard = plane
                plane.on_refresh_pass()
            server = (
                start_async(ext) if front_end == "async"
                else start_threaded(ext)
            )
            try:
                body = make_bodies(names, "nodenames", count=1)[0]
                wire[label] = {
                    path: raw_request(server.port, post_bytes(path, body))
                    for path in (
                        "/scheduler/prioritize", "/scheduler/filter",
                    )
                }
                text = ext.metrics_text()
                if label == "on":
                    assert "pas_shard_" in text
                else:
                    assert "pas_shard_" not in text
            finally:
                server.shutdown()
        drop = "x-request-id"
        for path, (status, headers, body) in wire["off_a"].items():
            b_status, b_headers, b_body = wire["off_b"][path]
            assert status == b_status == 200
            assert body == b_body
            assert {k: v for k, v in headers.items() if k != drop} == {
                k: v for k, v in b_headers.items() if k != drop
            }
        status, _headers, body = wire["on"]["/scheduler/filter"]
        assert status == 200
        assert body == wire["off_a"]["/scheduler/filter"][2]


class TestTraceFamilies:
    FAMILIES = (
        "pas_shard_ticks_total",
        "pas_shard_refresh_nodes_total",
        "pas_shard_digests_published_total",
        "pas_shard_gossip_ingested_total",
        "pas_shard_digest_fenced_total",
        "pas_shard_digest_stale_total",
        "pas_shard_gather_local_only_total",
        "pas_shard_gather_held_total",
        "pas_shard_gang_deferred_total",
    )

    def test_every_family_declared(self):
        for family in self.FAMILIES:
            assert family in trace.METRICS, f"undeclared {family!r}"
            kind, _help = trace.METRICS[family]
            assert kind == "counter"

    def test_wired_plane_exports_parseable_families(self):
        ext, names = seeded_extender(8)
        plane = static_plane("m0", 2, owners={0: "m0", 1: "m1"})
        plane.attach(ext.cache, ext.mirror)
        ext.shard = plane
        plane.on_refresh_pass()
        ext.cache.refresh_filter({n: object() for n in names})
        plane.store.put(make_digest(1, epoch=0))  # fenced
        text = ext.metrics_text()
        families = trace.parse_prometheus_text(text)
        for family in (
            "pas_shard_ticks_total",
            "pas_shard_refresh_nodes_total",
            "pas_shard_digest_fenced_total",
        ):
            assert family in families, family
        for family in families:
            assert family in trace.METRICS, f"undeclared {family!r}"


class TestHAHarnessShard:
    def test_partitioned_fleet_covers_the_world_once(self):
        from platform_aware_scheduling_tpu.testing.ha import HAHarness

        harness = HAHarness(
            replicas=3, num_nodes=12, shard_partitions=4, period_s=1.0
        )
        harness.run(4)
        owned = [
            stack.shard.coordinator.owned() for stack in harness.live()
        ]
        assert frozenset().union(*owned) == frozenset(range(4))
        for i, a in enumerate(owned):
            for b in owned[i + 1:]:
                assert a & b == frozenset()
        # every OWNED partition's nodes are interned in the owner's
        # mirror (the ~1/P ingest cut never starves a local solve)
        names = [f"node-{i}" for i in range(12)]
        for stack in harness.live():
            mine = {
                n for n in names
                if stack.shard.pmap.partition_of(n)
                in stack.shard.coordinator.owned()
            }
            view = stack.mirror.device_view()
            assert mine <= set(view.node_names)

    def test_crashed_owner_hands_partitions_to_survivors(self):
        from platform_aware_scheduling_tpu.testing.ha import HAHarness

        harness = HAHarness(
            replicas=3, num_nodes=12, shard_partitions=4, period_s=1.0,
            lease_duration_s=3.0,
        )
        harness.run(4)
        victim_index = next(
            i for i, stack in enumerate(harness.replicas)
            if stack.shard.coordinator.owned()
        )
        victim = harness.replicas[victim_index]
        lost = victim.shard.coordinator.owned()
        epochs_before = {
            p: max(
                s.shard.coordinator.epoch(p) for s in harness.live()
            )
            for p in lost
        }
        harness.crash(victim_index)
        harness.run(8)
        survivors = harness.live()
        merged = frozenset().union(
            *(s.shard.coordinator.owned() for s in survivors)
        )
        assert merged == frozenset(range(4))
        # every lost partition moved AND its fencing epoch advanced, so
        # a digest the victim stamped pre-crash can never land again
        for p in lost:
            assert harness.shard_owners()[p] != victim.identity
            epoch_now = max(
                s.shard.coordinator.epoch(p) for s in survivors
            )
            assert epoch_now > epochs_before[p]

"""Coverage-guided scenario fuzzing (testing/fuzz.py; docs/robustness.md
"Adversarial scenario search"): the seeded LCG, genome generation and
mutation, candidate determinism (the byte-identical-replay pin), the
coverage-novelty corpus, delta-debug minimization, versioned scenario
serialization, the planted-bug quick gate, and the randomness
discipline the whole layer rests on — every draw flows from an
injected seed (pascheck check ``randomness``)."""

import copy
import json
from pathlib import Path

import pytest

from platform_aware_scheduling_tpu.testing import fuzz
from platform_aware_scheduling_tpu.utils.events import JOURNAL

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _clean_journal():
    JOURNAL.reset()
    yield
    JOURNAL.reset()


class TestLCG:
    def test_deterministic_per_seed(self):
        a = [fuzz.LCG(42).u32() for _ in range(5)]
        assert a == [fuzz.LCG(42).u32() for _ in range(5)]
        assert a != [fuzz.LCG(43).u32() for _ in range(5)]

    def test_draw_ranges(self):
        rng = fuzz.LCG(7)
        for _ in range(200):
            assert 0.0 <= rng.random() < 1.0
            assert 3 <= rng.randint(3, 9) <= 9
            assert rng.choice(["a", "b"]) in ("a", "b")
            assert rng.chance(1.0) is True
            assert rng.chance(0.0) is False

    def test_process_independent_values(self):
        # pinned: the LCG is pure integer math, so these exact values
        # hold on every machine — the cross-run reproducibility pin
        rng = fuzz.LCG(7)
        assert [rng.u32() for _ in range(3)] == [
            2461488101, 3397525143, 4214469190,
        ]


class TestGenomes:
    def test_generated_genomes_validate_and_replay(self):
        for i in range(30):
            genome = fuzz.generate_genome(fuzz.LCG(i))
            fuzz.validate_genome(genome)
            again = fuzz.generate_genome(fuzz.LCG(i))
            assert genome == again, f"seed {i} not deterministic"

    def test_mutations_validate_and_are_deterministic(self):
        base = fuzz.generate_genome(fuzz.LCG(1))
        for i in range(30):
            mutant = fuzz.mutate_genome(fuzz.LCG(100 + i), base)
            fuzz.validate_genome(mutant)
            assert mutant == fuzz.mutate_genome(fuzz.LCG(100 + i), base)

    def test_validate_rejects_malformed_genomes(self):
        good = copy.deepcopy(fuzz.SEED_GENOMES[0])
        for breakage in (
            {"version": 999},
            {"mode": "bogus"},
            {"ticks": 0},
            {"ticks": 10_000},
            {"events": [{"type": "no_such_event", "t": 0}]},
            {"events": [{"type": "load_flat", "t": -1, "value": 100}]},
        ):
            bad = dict(copy.deepcopy(good), **breakage)
            with pytest.raises(ValueError):
                fuzz.validate_genome(bad)

    def test_digest_is_key_order_independent(self):
        genome = fuzz.SEED_GENOMES[0]
        reordered = json.loads(
            json.dumps(genome, sort_keys=True)[::-1][::-1]
        )
        assert fuzz.genome_digest(genome) == fuzz.genome_digest(reordered)
        other = copy.deepcopy(genome)
        other["ticks"] += 1
        assert fuzz.genome_digest(other) != fuzz.genome_digest(genome)

    def test_seed_genomes_cover_both_modes(self):
        modes = {g["mode"] for g in fuzz.SEED_GENOMES}
        assert modes == {"core", "admission"}
        for genome in fuzz.SEED_GENOMES:
            fuzz.validate_genome(genome)


class TestCandidateDeterminism:
    def test_run_candidate_is_byte_identical(self):
        # the faultiest seed genome: kills an owner mid-gossip-outage
        genome = fuzz.SEED_GENOMES[2]
        a = fuzz.run_candidate(genome)
        b = fuzz.run_candidate(genome)
        assert a == b
        assert a["verdict"] == "ok"
        assert a["coverage"], "a sharded run must emit coverage signals"

    def test_engine_sequences_are_reproducible(self):
        runs = []
        for _ in range(2):
            engine = fuzz.FuzzEngine(seed=7)
            engine.fuzz(max_candidates=8)
            runs.append(
                [
                    (r["digest"], r["verdict"], tuple(r["failures"]))
                    for r in engine.records
                ]
            )
        assert runs[0] == runs[1]
        assert len(runs[0]) == 8

    def test_quiet_genome_is_green_and_declared_quiet(self):
        genome = fuzz.SEED_GENOMES[0]
        assert fuzz.is_quiet_genome(genome)
        record = fuzz.run_candidate(genome)
        assert record["verdict"] == "ok", record


class TestCorpus:
    def test_coverage_novelty_admits_and_bounds_the_corpus(self):
        engine = fuzz.FuzzEngine(seed=7, max_corpus=2)
        engine.fuzz(max_candidates=6)
        # candidate #0 always lands (everything is novel at the start)
        assert engine.records[0]["new_signals"] > 0
        assert 0 < len(engine.corpus) <= 2
        # seen-signal set only grows, and records agree with it
        total_new = sum(r["new_signals"] for r in engine.records)
        assert total_new == len(engine.seen)

    def test_wall_budget_only_truncates_the_sequence(self):
        """A fake clock that expires after 3 candidates yields exactly
        the first 3 records of the untruncated run — budgets change how
        far the search gets, never what it computes."""
        full = fuzz.FuzzEngine(seed=7)
        full.fuzz(max_candidates=5)

        ticks = {"n": 0}

        def clock():
            ticks["n"] += 1
            return float(ticks["n"])

        short = fuzz.FuzzEngine(seed=7)
        short.fuzz(time_budget_s=3.0, clock=clock)
        truncated = [
            (r["digest"], r["verdict"]) for r in short.records
        ]
        prefix = [
            (r["digest"], r["verdict"])
            for r in full.records[: len(truncated)]
        ]
        assert truncated and truncated == prefix


class TestMinimize:
    def test_minimizer_drops_junk_and_keeps_the_failure(self):
        base = json.loads(
            (REPO / "tests/scenarios/lost_rebind.json").read_text()
        )["genome"]
        noisy = copy.deepcopy(base)
        noisy["ticks"] = 20
        noisy["events"].extend(
            [
                {"type": "load_flat", "t": 8, "value": 150},
                {"type": "knob", "t": 9, "name": "admission_depth",
                 "value": 32},
                {"type": "fault", "t": 10, "verb": "get_node_metric",
                 "op": "latency", "count": 2, "seconds": 1.0},
            ]
        )
        with fuzz.planted_bug("lost_rebind"):
            out = fuzz.minimize(noisy, ["oracle:population"])
        assert "oracle:population" in out["failures"]
        assert out["attempts"] > 0
        genome = out["genome"]
        assert len(genome["events"]) <= len(base["events"])
        assert genome["ticks"] <= base["ticks"]

    def test_minimizer_rejects_reductions_that_stop_failing(self):
        """On the healthy tree nothing fails, so every reduction is
        rejected and the genome comes back unchanged."""
        base = copy.deepcopy(fuzz.SEED_GENOMES[1])
        out = fuzz.minimize(base, ["oracle:population"], max_attempts=12)
        assert out["genome"] == base
        assert out["failures"] == []


class TestPlantedBugs:
    def test_stale_digest_splice_caught_by_seed_corpus(self):
        """The quick planted-bug gate: detection rides the hand-built
        seed corpus, not mutation luck."""
        genome = fuzz.SEED_GENOMES[2]
        assert fuzz.run_candidate(genome)["verdict"] == "ok"
        with fuzz.planted_bug("stale_digest_splice"):
            record = fuzz.run_candidate(genome)
        assert "oracle:shard_splice" in record["failures"]
        # the patch is scoped: healthy again outside the context
        assert fuzz.run_candidate(genome)["verdict"] == "ok"

    def test_unknown_plant_is_an_error(self):
        with pytest.raises(ValueError, match="unknown planted bug"):
            with fuzz.planted_bug("no_such_bug"):
                pass


class TestSerialization:
    def test_round_trip_through_disk(self, tmp_path):
        obj = fuzz.scenario_to_obj(
            fuzz.SEED_GENOMES[0],
            expect=["oracle:quiet"],
            planted=None,
            seed=7,
            notes="round trip",
        )
        path = tmp_path / "scn.json"
        fuzz.save_scenario(path, obj)
        scenario = fuzz.load_scenario(path)
        assert scenario.genome == fuzz.SEED_GENOMES[0]
        assert scenario.expect == ["oracle:quiet"]
        assert scenario.planted is None
        assert scenario.notes == "round trip"
        # text and dict sources load identically
        assert fuzz.load_scenario(
            path.read_text()
        ).genome == scenario.genome
        assert fuzz.load_scenario(obj).genome == scenario.genome

    def test_loader_rejects_foreign_formats(self):
        for fmt in (None, "pas-fuzz-scenario/2", "something-else"):
            with pytest.raises(ValueError, match="not a fuzz scenario"):
                fuzz.load_scenario({"format": fmt, "genome": {}})


class TestRandomnessDiscipline:
    def test_fuzz_layers_pass_the_randomness_check(self):
        """The checker that guards the reproducibility pin: nothing in
        the package or benchmarks/ draws from ambient RNG state."""
        from platform_aware_scheduling_tpu.analysis import randomness
        from platform_aware_scheduling_tpu.analysis.core import (
            load_modules,
        )

        for root in (
            REPO / "platform_aware_scheduling_tpu" / "testing",
            REPO / "benchmarks",
        ):
            modules, _pragma = load_modules(root)
            findings = randomness.check(modules)
            assert not findings, [f.render() for f in findings]

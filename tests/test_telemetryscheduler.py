"""TAS MetricsExtender verb tests — table-driven against pre-seeded caches,
mirroring reference pkg/telemetryscheduler/scheduler_test.go, plus
device-path vs host-path wire equivalence."""

import json

import numpy as np
import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def post(body: dict | bytes) -> HTTPRequest:
    raw = body if isinstance(body, bytes) else json.dumps(body).encode()
    return HTTPRequest(
        method="POST",
        path="/scheduler/prioritize",
        headers={"Content-Type": "application/json"},
        body=raw,
    )


def args_obj(pod_labels=None, node_names=None, namespace="default"):
    return {
        "Pod": {
            "metadata": {
                "name": "big pod",
                "namespace": namespace,
                "labels": pod_labels or {},
            }
        },
        "Nodes": {
            "items": [{"metadata": {"name": n}} for n in (node_names or [])]
        },
    }


def metric_info(**kv):
    return {n: NodeMetric(value=Quantity(str(v))) for n, v in kv.items()}


POLICY1 = make_policy(
    "policy1",
    strategies={
        "scheduleonmetric": [rule("metric1", "GreaterThan", 0)],
        "dontschedule": [rule("metric1", "GreaterThan", 40)],
    },
)


def build(with_mirror: bool):
    cache = AutoUpdatingCache()
    mirror = None
    if with_mirror:
        mirror = TensorStateMirror()
        mirror.attach(cache)
    cache.write_policy("default", "policy1", TASPolicy.from_obj(POLICY1))
    return cache, MetricsExtender(cache, mirror=mirror)


@pytest.fixture(params=[False, True], ids=["host", "device"])
def extender(request):
    cache, ext = build(request.param)
    return cache, ext


class TestPrioritize:
    def test_get_and_return_node(self, extender):
        cache, ext = extender
        cache.write_metric("metric1", metric_info(**{"node A": 100, "node B": 90}))
        resp = ext.prioritize(
            post(args_obj({"telemetry-policy": "policy1"}, ["node A", "node B"]))
        )
        assert resp.status == 200
        assert json.loads(resp.body) == [
            {"Host": "node A", "Score": 10},
            {"Host": "node B", "Score": 9},
        ]

    def test_policy_not_found_returns_empty(self, extender):
        cache, ext = extender
        cache.write_metric("metric1", metric_info(**{"node A": 100}))
        resp = ext.prioritize(
            post(args_obj({"telemetry-policy": "missing"}, ["node A"]))
        )
        assert resp.status == 200
        assert json.loads(resp.body) == []

    def test_empty_cache_returns_empty(self, extender):
        _, ext = extender
        resp = ext.prioritize(
            post(args_obj({"telemetry-policy": "policy1"}, ["node A"]))
        )
        assert json.loads(resp.body) == []

    def test_unlabelled_pod_gets_400_but_still_answers(self, extender):
        cache, ext = extender
        cache.write_metric("metric1", metric_info(**{"node A": 100}))
        resp = ext.prioritize(post(args_obj({}, ["node A"])))
        assert resp.status == 400
        assert json.loads(resp.body) == []

    def test_malformed_args_empty_200(self, extender):
        _, ext = extender
        resp = ext.prioritize(post(b"{not json"))
        assert resp.status == 200 and resp.body == b""
        resp = ext.prioritize(post({"Pod": {}}))  # Nodes nil
        assert resp.status == 200 and resp.body == b""

    def test_no_nodes_in_list_empty_200(self, extender):
        _, ext = extender
        resp = ext.prioritize(post(args_obj({"telemetry-policy": "policy1"}, [])))
        assert resp.status == 200 and resp.body == b""

    def test_lessthan_sorts_ascending(self, extender):
        cache, ext = extender
        policy = make_policy(
            "asc", strategies={"scheduleonmetric": [rule("m", "LessThan", 0)]}
        )
        cache.write_policy("default", "asc", TASPolicy.from_obj(policy))
        cache.write_metric("m", metric_info(a=30, b=10, c=20))
        resp = ext.prioritize(
            post(args_obj({"telemetry-policy": "asc"}, ["a", "b", "c"]))
        )
        assert json.loads(resp.body) == [
            {"Host": "b", "Score": 10},
            {"Host": "c", "Score": 9},
            {"Host": "a", "Score": 8},
        ]

    def test_candidates_missing_from_metric_skipped(self, extender):
        cache, ext = extender
        cache.write_metric("metric1", metric_info(**{"node A": 5}))
        resp = ext.prioritize(
            post(args_obj({"telemetry-policy": "policy1"}, ["node A", "ghost"]))
        )
        assert json.loads(resp.body) == [{"Host": "node A", "Score": 10}]

    def test_scores_go_negative_past_rank_10(self, extender):
        cache, ext = extender
        names = [f"n{i:02d}" for i in range(12)]
        cache.write_metric(
            "metric1", metric_info(**{n: 100 - i for i, n in enumerate(names)})
        )
        resp = ext.prioritize(
            post(args_obj({"telemetry-policy": "policy1"}, names))
        )
        out = json.loads(resp.body)
        assert out[0] == {"Host": "n00", "Score": 10}
        assert out[11] == {"Host": "n11", "Score": -1}


class TestFilter:
    def test_get_and_return_node(self, extender):
        cache, ext = extender
        cache.write_metric("metric1", metric_info(nodeA=10, nodeB=50))
        resp = ext.filter(
            post(args_obj({"telemetry-policy": "policy1"}, ["nodeA", "nodeB"]))
        )
        assert resp.status == 200
        out = json.loads(resp.body)
        assert [n["metadata"]["name"] for n in out["Nodes"]["items"]] == ["nodeA"]
        assert out["NodeNames"] == ["nodeA", ""]  # reference trailing-split quirk
        assert out["FailedNodes"] == {
            "nodeB": "policy policy1: metric metric1=50 > threshold 40"
        }
        assert out["Error"] == ""

    def test_no_policy_404_null(self, extender):
        _, ext = extender
        resp = ext.filter(post(args_obj({"telemetry-policy": "nope"}, ["node A"])))
        assert resp.status == 404
        assert resp.body == b"null\n"

    def test_no_dontschedule_strategy_404(self, extender):
        cache, ext = extender
        policy = make_policy(
            "som-only", strategies={"scheduleonmetric": [rule("m", "GreaterThan", 0)]}
        )
        cache.write_policy("default", "som-only", TASPolicy.from_obj(policy))
        resp = ext.filter(
            post(args_obj({"telemetry-policy": "som-only"}, ["node A"]))
        )
        assert resp.status == 404

    def test_empty_candidates_404(self, extender):
        cache, ext = extender
        cache.write_metric("metric1", metric_info(**{"node A": 10}))
        resp = ext.filter(post(args_obj({"telemetry-policy": "policy1"}, [])))
        assert resp.status == 404

    def test_all_violating(self, extender):
        cache, ext = extender
        cache.write_metric("metric1", metric_info(**{"node A": 99, "node B": 77}))
        resp = ext.filter(
            post(args_obj({"telemetry-policy": "policy1"}, ["node A", "node B"]))
        )
        out = json.loads(resp.body)
        assert out["Nodes"]["items"] is None
        assert out["NodeNames"] == [""]
        assert set(out["FailedNodes"]) == {"node A", "node B"}

    def test_metric_missing_passes_everything(self, extender):
        cache, ext = extender
        resp = ext.filter(
            post(args_obj({"telemetry-policy": "policy1"}, ["node A", "node B"]))
        )
        out = json.loads(resp.body)
        assert out["FailedNodes"] == {}
        assert [n["metadata"]["name"] for n in out["Nodes"]["items"]] == [
            "node A",
            "node B",
        ]


class TestBind:
    def test_bind_404(self, extender):
        _, ext = extender
        resp = ext.bind(post({}))
        assert resp.status == 404


class TestDeviceHostEquivalence:
    """Same cache state, same requests: device path output must byte-match
    the host path (the whole fidelity contract)."""

    @pytest.mark.parametrize("op", ["GreaterThan", "LessThan"])
    def test_prioritize_random_state(self, op):
        rng = np.random.default_rng(42)
        cache_h, ext_h = build(False)
        cache_d, ext_d = build(True)
        policy = make_policy(
            "p", strategies={"scheduleonmetric": [rule("m", op, 0)]}
        )
        names = [f"node{i}" for i in range(50)]
        # distinct values so ordering is unique (tie order differs by design)
        vals = rng.permutation(np.arange(-25_000, 25_000, 1000))[: len(names)]
        info = metric_info(**{n: int(v) for n, v in zip(names, vals)})
        for cache in (cache_h, cache_d):
            cache.write_policy("default", "p", TASPolicy.from_obj(policy))
            cache.write_metric("m", info)
        req = post(args_obj({"telemetry-policy": "p"}, names[:40]))
        assert ext_h.prioritize(req).body == ext_d.prioritize(req).body

    def test_filter_random_state(self):
        rng = np.random.default_rng(43)
        cache_h, ext_h = build(False)
        cache_d, ext_d = build(True)
        policy = make_policy(
            "p",
            strategies={
                "dontschedule": [
                    rule("m1", "GreaterThan", 50),
                    rule("m2", "LessThan", -10),
                ]
            },
        )
        names = [f"node{i}" for i in range(60)]
        m1 = metric_info(
            **{n: int(rng.integers(0, 100)) for n in names if rng.random() > 0.2}
        )
        m2 = metric_info(
            **{n: int(rng.integers(-50, 50)) for n in names if rng.random() > 0.2}
        )
        for cache in (cache_h, cache_d):
            cache.write_policy("default", "p", TASPolicy.from_obj(policy))
            cache.write_metric("m1", m1)
            cache.write_metric("m2", m2)
        req = post(args_obj({"telemetry-policy": "p"}, names))
        assert ext_h.filter(req).body == ext_d.filter(req).body

    def test_device_path_actually_used(self):
        cache, ext = build(True)
        cache.write_metric("metric1", metric_info(**{"node A": 100}))
        # sabotage the host cache read to prove the device path answered
        compiled = ext.mirror.policy("default", "policy1")
        assert compiled is not None and compiled.scheduleonmetric_row >= 0
        orig = ext.cache.read_metric
        ext.cache.read_metric = lambda name: (_ for _ in ()).throw(AssertionError())
        try:
            resp = ext.prioritize(
                post(args_obj({"telemetry-policy": "policy1"}, ["node A"]))
            )
            assert json.loads(resp.body) == [{"Host": "node A", "Score": 10}]
        finally:
            ext.cache.read_metric = orig

    def test_host_only_metric_falls_back(self):
        cache, ext = build(True)
        # sub-milli value: inexact -> host path must serve it
        cache.write_metric(
            "metric1",
            {
                "node A": NodeMetric(value=Quantity("100500u")),
                "node B": NodeMetric(value=Quantity("2")),
            },
        )
        assert ext.mirror.metric_host_only("metric1")
        resp = ext.prioritize(
            post(args_obj({"telemetry-policy": "policy1"}, ["node A", "node B"]))
        )
        assert json.loads(resp.body) == [
            {"Host": "node B", "Score": 10},
            {"Host": "node A", "Score": 9},
        ]

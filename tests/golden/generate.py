"""Deterministic generator for the golden REQUEST fixtures.

Emits byte-exact bodies as Go would marshal them (compact separators,
struct field order, zero-value quirks like ``"creationTimestamp":null``).
Request fixtures are committed; re-run this after editing and commit the
diff.  Response goldens are pinned separately by test_golden_wire.py
against the canned cache state (see README.md here).

Derivation: upstream k8s.io/kube-scheduler/extender/v1 ExtenderArgs /
ExtenderBindingArgs tags for the `*_upstream*` family; the reference's
untagged structs (extender/types.go:41-76) for `*_reference_style`.
Object shapes follow what a kind cluster's API server serves for nodes
and a scheduler-bound pod.
"""

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

NODE_NAMES = ["gw-a", "gw-b", "gw-c", "gw-d"]


def compact(obj) -> bytes:
    # Go json.Marshal writes compact JSON with no spaces
    return json.dumps(obj, separators=(",", ":")).encode()


def pod_obj():
    """A scheduler-POSTed v1.Pod, Go-marshaled: struct field order,
    creationTimestamp null, status present-but-sparse."""
    return {
        "metadata": {
            "name": "golden-pod",
            "namespace": "default",
            "uid": "8f2a7e6c-1d4b-4e9a-bb2e-000000000001",
            "resourceVersion": "12345",
            "creationTimestamp": None,
            "labels": {"telemetry-policy": "golden-pol"},
            "annotations": {
                "kubernetes.io/psp": "kind-default",
            },
        },
        "spec": {
            "volumes": [
                {
                    "name": "kube-api-access-x7k2p",
                    "projected": {
                        "sources": [
                            {
                                "serviceAccountToken": {
                                    "expirationSeconds": 3607,
                                    "path": "token",
                                }
                            }
                        ],
                        "defaultMode": 420,
                    },
                }
            ],
            "containers": [
                {
                    "name": "workload",
                    "image": "busybox:1.36",
                    "command": ["sleep", "3600"],
                    "resources": {
                        "limits": {"telemetry/scheduling": "1"},
                        "requests": {"telemetry/scheduling": "1"},
                    },
                    "volumeMounts": [
                        {
                            "name": "kube-api-access-x7k2p",
                            "readOnly": True,
                            "mountPath": "/var/run/secrets/kubernetes.io/serviceaccount",
                        }
                    ],
                    "terminationMessagePath": "/dev/termination-log",
                    "terminationMessagePolicy": "File",
                    "imagePullPolicy": "IfNotPresent",
                }
            ],
            "restartPolicy": "Always",
            "terminationGracePeriodSeconds": 30,
            "dnsPolicy": "ClusterFirst",
            "serviceAccountName": "default",
            "serviceAccount": "default",
            "securityContext": {},
            "schedulerName": "default-scheduler",
            "tolerations": [
                {
                    "key": "node.kubernetes.io/not-ready",
                    "operator": "Exists",
                    "effect": "NoExecute",
                    "tolerationSeconds": 300,
                },
                {
                    "key": "node.kubernetes.io/unreachable",
                    "operator": "Exists",
                    "effect": "NoExecute",
                    "tolerationSeconds": 300,
                },
            ],
            "priority": 0,
            "enableServiceLinks": True,
            "preemptionPolicy": "PreemptLowerPriority",
        },
        "status": {"phase": "Pending", "qosClass": "BestEffort"},
    }


def node_obj(name: str, ordinal: int):
    """A kind-style v1.Node as the API server serves it."""
    return {
        "metadata": {
            "name": name,
            "uid": f"6c0e7d2a-0000-4000-8000-00000000000{ordinal}",
            "resourceVersion": str(9000 + ordinal),
            "creationTimestamp": None,
            "labels": {
                "beta.kubernetes.io/arch": "amd64",
                "beta.kubernetes.io/os": "linux",
                "kubernetes.io/arch": "amd64",
                "kubernetes.io/hostname": name,
                "kubernetes.io/os": "linux",
            },
            "annotations": {
                "kubeadm.alpha.kubernetes.io/cri-socket": "unix:///run/containerd/containerd.sock",
                "node.alpha.kubernetes.io/ttl": "0",
                "volumes.kubernetes.io/controller-managed-attach-detach": "true",
            },
        },
        "spec": {
            "podCIDR": f"10.244.{ordinal}.0/24",
            "podCIDRs": [f"10.244.{ordinal}.0/24"],
            "providerID": f"kind://docker/golden/{name}",
        },
        "status": {
            "capacity": {
                "cpu": "8",
                "ephemeral-storage": "263174212Ki",
                "hugepages-2Mi": "0",
                "memory": "32658828Ki",
                "pods": "110",
            },
            "allocatable": {
                "cpu": "8",
                "ephemeral-storage": "263174212Ki",
                "hugepages-2Mi": "0",
                "memory": "32658828Ki",
                "pods": "110",
            },
            "conditions": [
                {
                    "type": "Ready",
                    "status": "True",
                    "lastHeartbeatTime": "2026-07-29T00:00:00Z",
                    "lastTransitionTime": "2026-07-29T00:00:00Z",
                    "reason": "KubeletReady",
                    "message": "kubelet is posting ready status",
                }
            ],
            "addresses": [
                {"type": "InternalIP", "address": f"172.18.0.{ordinal + 2}"},
                {"type": "Hostname", "address": name},
            ],
            "daemonEndpoints": {"kubeletEndpoint": {"Port": 10250}},
            "nodeInfo": {
                "machineID": f"machine-{ordinal}",
                "systemUUID": f"system-{ordinal}",
                "bootID": f"boot-{ordinal}",
                "kernelVersion": "6.1.0",
                "osImage": "Debian GNU/Linux 12 (bookworm)",
                "containerRuntimeVersion": "containerd://1.7.1",
                "kubeletVersion": "v1.30.0",
                "kubeProxyVersion": "v1.30.0",
                "operatingSystem": "linux",
                "architecture": "amd64",
            },
        },
    }


def node_list():
    return {
        "metadata": {},
        "items": [node_obj(n, i) for i, n in enumerate(NODE_NAMES)],
    }


def main(out_dir: str = HERE):
    def write(name: str, data: bytes) -> None:
        with open(os.path.join(out_dir, name), "wb") as f:
            f.write(data)

    # upstream kube-scheduler spellings (lowercase tags, omitempty)
    write(
        "prioritize_request_upstream.json",
        compact({"pod": pod_obj(), "nodes": node_list()}),
    )
    write(
        "prioritize_request_upstream_nodenames.json",
        compact({"pod": pod_obj(), "nodenames": NODE_NAMES}),
    )
    write(
        "bind_request_upstream.json",
        compact(
            {
                "podName": "golden-pod",
                "podNamespace": "default",
                "podUID": "8f2a7e6c-1d4b-4e9a-bb2e-000000000001",
                "node": "gw-b",
            }
        ),
    )
    # the reference's untagged-struct spellings (all fields, null absents)
    write(
        "prioritize_request_reference_style.json",
        compact(
            {"Pod": pod_obj(), "Nodes": node_list(), "NodeNames": None}
        ),
    )
    write(
        "prioritize_request_reference_style_nodenames.json",
        compact({"Pod": pod_obj(), "Nodes": None, "NodeNames": NODE_NAMES}),
    )


if __name__ == "__main__":
    import sys

    main(sys.argv[1] if len(sys.argv) > 1 else HERE)

"""Extract golden wire fixtures from a kind-e2e wire capture.

The CI e2e job runs TAS at ``--v=5``, where the server dumps every
request/response pair (extender/server.py WIRE lines), and uploads the
pod log as the ``wire-capture`` artifact.  This tool turns that log back
into fixture files — the refresh path for ``tests/golden/`` from a REAL
kube-scheduler:

    python tests/golden/from_capture.py wire-capture/tas.log out_dir/

Each pair becomes ``<n>_<verb>_request.json`` + ``<n>_<verb>_response.json``
with a small index.json describing what was captured.  Review, pick
representative pairs, and commit them alongside the hand-derived
fixtures (generate.py) with updated expectations.
"""

import base64
import binascii
import json
import os
import re
import sys

# bodies are base64 on one line (extender/server.py v5 dump): recovery is
# byte-exact — trailing newlines survive, and no body content can collide
# with the log format's own delimiters.  The explicit len= guards against
# log-line truncation: a cut base64 string can still decode "validly" if
# the cut lands on a 4-char boundary, but its length won't match.
WIRE_REQ = re.compile(
    r"WIRE request POST /scheduler/(\w+) len=(\d+) b64=([A-Za-z0-9+/=]*)"
)
WIRE_RESP = re.compile(
    r"WIRE response /scheduler/(\w+) status=(\d+) len=(\d+) "
    r"b64=([A-Za-z0-9+/=]*)"
)


def _decode_checked(length_str: str, b64_str: str):
    """bytes or None: base64 must validate AND match the declared length."""
    try:
        body = base64.b64decode(b64_str, validate=True)
    except binascii.Error:
        return None
    return body if len(body) == int(length_str) else None


def extract(log_text: str):
    """Yield (verb, request bytes, status, response bytes) in log order.
    Pairing is FIFO per verb: each response matches the OLDEST unanswered
    request for that verb.

    Caveat: FIFO is only guaranteed correct for sequential traffic — the
    threaded server may interleave concurrent requests' log lines out of
    completion order.  The kind e2e scenarios drive requests one at a
    time (.github/e2e/run_e2e.py), so their capture pairs exactly;
    captures from a busy production scheduler should be taken during a
    quiet window or reviewed pair-by-pair before committing."""
    pending = {}
    for line in log_text.splitlines():
        m = WIRE_REQ.search(line)
        if m:
            body = _decode_checked(m.group(2), m.group(3))
            if body is None:
                # truncated request line: poison this verb's queue with a
                # placeholder so its (valid) response is consumed against
                # it and discarded — pairing order survives
                pending.setdefault(m.group(1), []).append(None)
            else:
                pending.setdefault(m.group(1), []).append(body)
            continue
        m = WIRE_RESP.search(line)
        if m:
            verb, status = m.group(1), int(m.group(2))
            queue = pending.get(verb)
            body = _decode_checked(m.group(3), m.group(4))
            if body is None:
                # truncated response line: its request must be consumed
                # too, or every later pair for this verb shifts by one
                if queue:
                    queue.pop(0)
                continue
            if queue:
                request_body = queue.pop(0)
                if request_body is not None:
                    yield verb, request_body, status, body


def main(log_path: str, out_dir: str) -> int:
    with open(log_path, encoding="utf-8", errors="replace") as f:
        text = f.read()
    os.makedirs(out_dir, exist_ok=True)
    index = []
    for i, (verb, req, status, resp) in enumerate(extract(text)):
        req_name = f"{i:03d}_{verb}_request.json"
        resp_name = f"{i:03d}_{verb}_response.json"
        with open(os.path.join(out_dir, req_name), "wb") as f:
            f.write(req)
        with open(os.path.join(out_dir, resp_name), "wb") as f:
            f.write(resp)
        entry = {"verb": verb, "status": status, "request": req_name,
                 "response": resp_name}
        try:  # annotate with the candidate count for easy picking
            obj = json.loads(req)
            lowered = {k.lower(): v for k, v in obj.items()}
            names = lowered.get("nodenames")
            nodes = lowered.get("nodes") or {}
            entry["candidates"] = (
                len(names) if names else len(nodes.get("items") or [])
            )
        except (json.JSONDecodeError, AttributeError):
            pass
        index.append(entry)
    with open(os.path.join(out_dir, "index.json"), "w") as f:
        json.dump(index, f, indent=1)
    print(f"extracted {len(index)} wire pairs to {out_dir}")
    return 0 if index else 1


if __name__ == "__main__":
    if len(sys.argv) != 3:
        print(__doc__)
        raise SystemExit(2)
    raise SystemExit(main(sys.argv[1], sys.argv[2]))

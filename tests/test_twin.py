"""Digital-twin suite (testing/twin.py; docs/observability.md "SLOs &
error budgets"): every default scenario program at tier-1 scale, the
metric-storm ACCEPTANCE scenario end to end over real sockets on both
front-ends (healthy -> burn-rate page -> recovery with a fake-clock-
consistent budget ledger), and the 100k-node tier behind ``-m slow``."""

import json
from pathlib import Path

import pytest

from platform_aware_scheduling_tpu.testing import fuzz
from platform_aware_scheduling_tpu.testing import twin as tw
from platform_aware_scheduling_tpu.utils import trace
from wirehelpers import get_request

SCENARIO_DIR = Path(__file__).resolve().parent / "scenarios"
SCENARIO_FILES = sorted(SCENARIO_DIR.glob("*.json"))

SMALL = {
    "num_nodes": 16,
    "pods": 16,
    "period_s": 5.0,
    "requests_per_tick": 1,
}


def _failures(result):
    return [c for c in result["checks"] if not c["ok"]]


class TestScenarioMatrix:
    @pytest.mark.parametrize(
        "scenario_cls",
        [
            tw.DiurnalLoad,
            tw.DeploymentWave,
            tw.NodeFailureWave,
            tw.MetricStorm,
            tw.LeaderKillComposite,
            tw.GangWave,
            tw.PartitionHandoff,
        ],
        ids=lambda cls: cls.name,
    )
    def test_default_scenario_passes_its_gates(self, scenario_cls):
        result = scenario_cls().run(SMALL)
        assert result["passed"], _failures(result)
        assert result["traffic"]["errors"] == 0, result["traffic"]

    def test_run_matrix_shape(self):
        out = tw.run_matrix(
            num_nodes=12,
            pods=12,
            scenarios=(tw.DiurnalLoad(),),
        )
        assert out["all_passed"] is True
        assert set(out["scenarios"]) == {"diurnal"}
        diurnal = out["scenarios"]["diurnal"]
        assert diurnal["judgment"]["telemetry_freshness"]["alert"] == "ok"

    def test_verdict_is_the_engines_judgment(self):
        """The twin's per-scenario verdict reads the SLO engine, not a
        parallel bookkeeping structure: failing an objective in the
        engine flips the scenario's gate."""
        scenario = tw.DiurnalLoad()
        twin = scenario.build(dict(SMALL))
        try:
            for t in range(6):
                scenario.apply(twin, t)
                twin.tick()
            # sabotage the engine's view: an impossible latency SLO
            twin.engine.slos["prioritize_p99"] = tw.SLO(
                name="prioritize_p99",
                sli="latency",
                objective=0.99,
                verbs=("prioritize",),
                threshold_s=1e-9,
            )
            twin.tick()
            checks = scenario.checks(twin)
            failed = {
                c["check"] for c in checks if not c["ok"]
            }
            assert "slo:prioritize_p99" in failed
        finally:
            twin.close()


class TestTwinMechanics:
    def test_rebind_keeps_pod_population(self):
        scenario = tw.DeploymentWave()
        twin = scenario.build(dict(SMALL))
        try:
            for t in range(scenario.ticks(SMALL)):
                scenario.apply(twin, t)
                twin.tick()
            assert len(twin.evictions()) > 0
            with twin.fake._lock:
                pod_count = len(twin.fake._pods)
            # 16 seed pods + the wave's deployment, none lost to churn
            assert pod_count == 16 + len(scenario._hot(twin))
        finally:
            twin.close()

    def test_fail_nodes_moves_pods_and_traffic(self):
        twin = tw.TwinCluster(**SMALL)
        try:
            twin.tick()
            twin.fail_nodes(["node-15", "node-14"])
            twin.tick()
            with twin.fake._lock:
                on_dead = [
                    raw
                    for raw in twin.fake._pods.values()
                    if (raw.get("spec") or {}).get("nodeName")
                    in twin.failed_nodes
                ]
            assert not on_dead
            assert "node-15" not in twin.live_node_names()
            # published telemetry no longer carries the dead nodes
            info = twin.metrics.get_node_metric(tw.METRIC)
            assert "node-15" not in info
        finally:
            twin.close()

    def test_restart_rewires_the_observability_plane(self):
        twin = tw.TwinCluster(**SMALL)
        try:
            twin.tick()
            stack = twin.restart(0)
            assert stack.extender.slo is twin.engine
            assert stack.extender.recorder in twin.engine.recorders
            twin.tick()  # traffic through the restarted replica judges
            assert twin.traffic["errors"] == 0
        finally:
            twin.close()

    def test_gas_lane_serves_real_filters(self):
        twin = tw.TwinCluster(**SMALL)
        try:
            twin.tick()
            assert twin.traffic["errors"] == 0
            summary = twin.gas.recorder.summary("gas_filter")
            assert summary["count"] >= 1
            assert "gas_filter_p99" in twin.engine.slos
        finally:
            twin.close()


class TestMetricStormAcceptance:
    """ISSUE 10 acceptance: healthy -> page-tier alert (burn rate
    crosses threshold, breach counted, /debug/slo names the violator)
    -> recovery with fake-clock-consistent error budget accounting,
    observed END TO END over a real socket on both front-ends."""

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_storm_over_a_real_socket(self, serving):
        scenario = tw.MetricStorm()
        scale = dict(SMALL)
        twin = scenario.build(scale)
        server = twin.serve(serving)
        try:
            port = server.port
            total = scenario.ticks(scale)
            storm_end = scenario.healthy_ticks + scenario.storm_ticks

            def slo_row(name):
                status, _h, body = get_request(port, "/debug/slo")
                assert status == 200
                snap = json.loads(body)
                return next(
                    row for row in snap["slos"] if row["name"] == name
                )

            def burn_gauge(window):
                status, _h, body = get_request(port, "/metrics")
                assert status == 200
                families = trace.parse_prometheus_text(body.decode())
                for _n, labels, value in families["pas_slo_burn_rate"][
                    "samples"
                ]:
                    if (
                        labels.get("slo") == "telemetry_freshness"
                        and labels.get("window") == window
                    ):
                        return value
                raise AssertionError("burn-rate series missing")

            paged_tick = None
            for t in range(total):
                scenario.apply(twin, t)
                twin.tick()
                if t == scenario.healthy_ticks - 1:
                    # healthy phase: compliant, no burn, no alert
                    row = slo_row("telemetry_freshness")
                    assert row["alert"] == "ok"
                    assert row["compliance"] == 1.0
                    assert burn_gauge("5m") == 0.0
                if (
                    paged_tick is None
                    and scenario.healthy_ticks <= t < storm_end
                ):
                    row = slo_row("telemetry_freshness")
                    if row["alert"] == "page":
                        paged_tick = t
                        # the gauge crossed the page threshold on BOTH
                        # fast windows, and the breach was counted
                        slo = twin.engine.slos["telemetry_freshness"]
                        assert burn_gauge("5m") >= slo.page_burn
                        assert burn_gauge("1h") >= slo.page_burn
                        assert row["breaches"]["page"] == 1
            assert paged_tick is not None, "storm must reach page tier"
            result = {
                "name": scenario.name,
                "checks": scenario.checks(twin),
            }
            failures = [c for c in result["checks"] if not c["ok"]]
            assert not failures, failures
            # recovery, over the wire: page cleared, fast window
            # drained, and the budget ledger kept the storm's seconds
            row = slo_row("telemetry_freshness")
            assert row["alert"] != "page"
            assert burn_gauge("5m") == 0.0
            bad_s = row["cumulative"]["total"] - row["cumulative"]["good"]
            storm_s = scenario.storm_ticks * twin.period_s
            assert 0 < bad_s <= storm_s + 2 * twin.period_s
            assert row["error_budget_remaining"] == pytest.approx(
                1.0 - row["burn_rate"]["3d"], abs=1e-6
            )
        finally:
            server.shutdown()
            twin.close()


class TestCommittedFuzzScenarios:
    """Every minimized find committed under tests/scenarios/ is a
    first-class regression (docs/robustness.md "Adversarial scenario
    search"): auto-discovered, loaded through ``twin.load_scenario``,
    and held to the replay contract — green on the healthy tree, and
    (when the find came from a planted bug) still detecting its bug
    class when the plant is re-applied.  Scenarios with no plant pin a
    REAL bug that was fixed in-tree; green forever IS their assertion."""

    def test_scenarios_are_committed(self):
        # the suite below parametrizes over the directory; an empty
        # glob would silently skip the whole contract
        assert len(SCENARIO_FILES) >= 2, SCENARIO_DIR

    @pytest.mark.parametrize(
        "path", SCENARIO_FILES, ids=lambda p: p.stem
    )
    def test_replays_green_on_the_healthy_tree(self, path):
        scenario = tw.load_scenario(path)
        result = scenario.run()
        assert result["passed"], _failures(result)

    @pytest.mark.parametrize(
        "path", SCENARIO_FILES, ids=lambda p: p.stem
    )
    def test_detects_its_bug_class_when_replanted(self, path):
        scenario = tw.load_scenario(path)
        if not scenario.planted:
            pytest.skip(
                "pins a fixed real bug — no plant to re-apply; the "
                "healthy-tree replay above is the whole contract"
            )
        with fuzz.planted_bug(scenario.planted):
            record = fuzz.run_candidate(scenario.genome)
        assert set(scenario.expect) & set(record["failures"]), record

    def test_loader_rejects_unknown_format(self):
        with pytest.raises(ValueError, match="pas-fuzz-scenario"):
            tw.load_scenario({"format": "pas-fuzz-scenario/999"})


@pytest.mark.slow
class TestClusterScale:
    """The 100k-node tier (ROADMAP item 5's scale claim): same code,
    bigger constructor arguments — a longer period amortizes the fixed
    5m page window over fewer, heavier ticks."""

    def test_metric_storm_at_100k_nodes(self):
        result = tw.MetricStorm().run(
            {
                "num_nodes": 100_000,
                "pods": 100_000,
                "period_s": 30.0,
                "requests_per_tick": 1,
                "latency_threshold_ms": 1000.0,
                "gas": False,
            }
        )
        assert result["num_nodes"] == 100_000
        assert result["passed"], _failures(result)

"""bench.py's printed JSON line layout.

The driver captures the TAIL of bench.py's stdout; rounds 3 and 4 both
lost the headline to front-truncation (BENCH_r0{3,4}.json ``parsed:
null``).  These tests pin the fix: the required fields — the
``speedup_p99*`` aliases and {metric, value, unit, vs_baseline} — are the
LAST keys of the line, and the bulky per-config latency dicts never
appear in the line at all (they go to the on-disk detail file).
"""

import importlib.util
import json
import os

_spec = importlib.util.spec_from_file_location(
    "bench",
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py"),
)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _fake_load():
    stats = {"count": 8, "p50_ms": 1.0, "p90_ms": 2.0, "p99_ms": 3.0,
             "mean_ms": 1.5, "requests_per_s": 100.0}
    return {
        "num_nodes": 100,
        "device": {"prioritize_nodenames_c1": dict(stats)},
        "control": {"prioritize_nodenames_c1": dict(stats)},
        "speedup": {"prioritize_nodenames_c1": {"p50": 10.0, "p99": 12.0}},
        "p99_prioritize_ms_device": 3.0,
        "p99_prioritize_ms_control": 36.0,
        "speedup_p99": 12.0,
        "speedup_p99_miss": 8.0,
        "speedup_p99_filter": 9.0,
    }


HEADLINE = {
    "metric": "batch_schedule_pods_per_sec_10k_nodes_1k_pods",
    "value": 123.4,
    "unit": "pods/s",
    "vs_baseline": 56.7,
}


class TestBenchLine:
    def test_headline_fields_are_last(self):
        result, _ = bench.assemble_line(HEADLINE, _fake_load(), {"c": 1})
        keys = list(result)
        assert keys[-4:] == ["metric", "value", "unit", "vs_baseline"]
        # aliases sit directly before the headline block
        alias_block = keys[: -4][-5:]
        assert "speedup_p99" in alias_block
        assert "p99_prioritize_ms_device" in alias_block

    def test_tail_window_parses_headline(self):
        """Any tail window that catches the closing brace catches every
        required field: the headline must live within the last 600 bytes
        of the serialized line."""
        result, _ = bench.assemble_line(HEADLINE, _fake_load(), {"c": 1})
        line = json.dumps(result)
        tail = line[-600:]
        for fragment in ('"vs_baseline"', '"metric"', '"speedup_p99"'):
            assert fragment in tail

    def test_bulk_detail_not_in_line(self):
        result, detail = bench.assemble_line(HEADLINE, _fake_load(), None)
        line = json.dumps(result)
        assert '"p90_ms"' not in line  # per-config stats stay off the line
        assert "device" in detail["http_load"]
        assert "control" in detail["http_load"]
        assert result["http_load"] == {
            "speedup": {"prioritize_nodenames_c1": {"p50": 10.0, "p99": 12.0}}
        }

    def test_missing_load_still_emits_headline(self):
        result, detail = bench.assemble_line(HEADLINE, None, None)
        assert list(result)[-4:] == ["metric", "value", "unit", "vs_baseline"]
        assert detail == {}
        # no http_load data -> no filter_miss caveat about it
        assert "notes" not in result

    def test_gas_section_compact_in_line(self):
        gas = {
            "num_nodes": 2000,
            "device": {"gas_filter_c1": {"p50_ms": 1.0, "p99_ms": 2.0}},
            "control": {"gas_filter_c1": {"p50_ms": 30.0, "p99_ms": 40.0}},
            "speedup": {"gas_filter_c1": {"p50": 30.0, "p99": 20.0}},
            "speedup_p99_gas_filter": 20.0,
        }
        result, detail = bench.assemble_line(HEADLINE, None, None, gas)
        assert result["gas_filter"]["speedup_p99_gas_filter"] == 20.0
        assert "device" not in result["gas_filter"]
        assert detail["gas_filter"]["device"]
        assert list(result)[-4:] == ["metric", "value", "unit", "vs_baseline"]

    def test_absent_aliases_are_omitted(self):
        load = _fake_load()  # has no *_c8 aliases (c1-only sweep)
        result, _ = bench.assemble_line(HEADLINE, load, None)
        assert "speedup_p99_c8" not in result
        assert result["speedup_p99"] == 12.0

    def test_serving_section_compact_in_line(self):
        stats = {"count": 8, "p50_ms": 1.0, "p99_ms": 2.0,
                 "requests_per_s": 100.0}
        serving = {
            "num_nodes": 100,
            "threaded": {"c1": dict(stats), "c8": dict(stats),
                         "p99_scaling_c8": 9.5, "rps_scaling_c8": 1.0},
            "async": {"c1": dict(stats), "c8": dict(stats),
                      "p99_scaling_c8": 2.1, "rps_scaling_c8": 3.0},
        }
        result, detail = bench.assemble_line(
            HEADLINE, None, None, serving=serving
        )
        # per-concurrency dicts go to the detail file, ratios to the line
        assert detail["serving_scaling"] == serving
        assert result["serving_scaling"]["async"] == {
            "p99_scaling_c8": 2.1, "rps_scaling_c8": 3.0
        }
        assert "c1" not in result["serving_scaling"]["threaded"]
        # headline keys still last
        assert list(result)[-4:] == ["metric", "value", "unit", "vs_baseline"]


class TestDetailPath:
    """_detail_path round resolution (ADVICE r5 #3): explicit override
    beats the env var beats glob inference."""

    def test_explicit_override(self):
        assert bench._detail_path(7).endswith("BENCH_DETAIL_r07.json")
        assert bench._detail_path("3").endswith("BENCH_DETAIL_r03.json")

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("PAS_TPU_BENCH_ROUND", "9")
        assert bench._detail_path().endswith("BENCH_DETAIL_r09.json")
        # the argument still wins over the env var
        assert bench._detail_path(4).endswith("BENCH_DETAIL_r04.json")

    def test_glob_inference_fallback(self, monkeypatch, tmp_path):
        """Inference over a seeded directory: one past the highest
        driver-written round, 0 when none exist."""
        monkeypatch.delenv("PAS_TPU_BENCH_ROUND", raising=False)
        # _detail_path roots its glob at bench.py's own directory
        monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
        assert bench._detail_path().endswith("BENCH_DETAIL_r00.json")
        for n in (0, 3, 11):
            (tmp_path / f"BENCH_r{n:02d}.json").write_text("{}")
        (tmp_path / "BENCH_rxx.json").write_text("{}")  # ignored: no digits
        assert bench._detail_path() == str(tmp_path / "BENCH_DETAIL_r12.json")

"""Health & readiness (docs/observability.md): /healthz liveness,
/readyz flipping 503 -> 200 exactly when every documented condition
(warm + synced + fresh + unsaturated) holds — each condition toggled
independently on BOTH front-ends — queue-bypass under saturation (same
bar as /metrics), the telemetry-freshness condition over a real refresh
loop, readiness flap counting, and the log <-> trace request-id join.
"""

import json
import logging
import threading
import time

import pytest

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
    Server,
)
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import (
    DummyMetricsClient,
    NodeMetric,
)
from platform_aware_scheduling_tpu.utils import health, klog, trace
from platform_aware_scheduling_tpu.utils.quantity import Quantity
from platform_aware_scheduling_tpu.utils.tracing import CounterSet
from wirehelpers import (
    get_request as _get,
    post_bytes as _post,
    raw_request as _raw,
    start_async as _start_async,
    start_threaded as _start_threaded,
)

CONDITIONS = ("kernels_warmed", "cache_synced", "telemetry_fresh")


class FlagScheduler:
    """A scheduler whose readiness conditions are test-controlled flags."""

    def __init__(self):
        self.flags = {name: True for name in CONDITIONS}

    def readiness_conditions(self):
        def check_for(name):
            def check():
                ok = self.flags[name]
                return ok, ("ok" if ok else f"{name} is down")

            return check

        return [(name, check_for(name)) for name in CONDITIONS]

    def metrics_text(self) -> str:
        return ""

    def prioritize(self, request):
        return HTTPResponse.json(b"[]\n")

    filter = prioritize

    def bind(self, request):
        return HTTPResponse(status=404)


def _readyz(port):
    status, _headers, payload = _get(port, "/readyz")
    return status, json.loads(payload)


class TestReadyzConditionToggling:
    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_flips_503_to_200_per_condition(self, serving):
        """ISSUE 3 acceptance: /readyz is 200 exactly when ALL conditions
        hold; flipping each condition independently flips the endpoint,
        and the failing condition is named in the JSON reasons."""
        scheduler = FlagScheduler()
        server = (
            _start_threaded(scheduler)
            if serving == "threaded"
            else _start_async(scheduler)
        )
        try:
            status, body = _readyz(server.port)
            assert status == 200 and body["ready"] is True
            reported = {c["name"] for c in body["conditions"]}
            assert set(CONDITIONS) <= reported
            for name in CONDITIONS:
                scheduler.flags[name] = False
                status, body = _readyz(server.port)
                assert status == 503, f"{name} down must unready"
                assert body["ready"] is False
                failing = {
                    c["name"]: c["reason"]
                    for c in body["conditions"]
                    if not c["ok"]
                }
                assert set(failing) == {name}
                assert f"{name} is down" in failing[name]
                scheduler.flags[name] = True
                status, body = _readyz(server.port)
                assert status == 200, f"{name} restored must re-ready"
        finally:
            server.shutdown()

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_healthz_always_200_and_get_only(self, serving):
        scheduler = FlagScheduler()
        scheduler.flags["kernels_warmed"] = False  # unready != unhealthy
        server = (
            _start_threaded(scheduler)
            if serving == "threaded"
            else _start_async(scheduler)
        )
        try:
            status, _headers, payload = _get(server.port, "/healthz")
            assert status == 200
            assert json.loads(payload) == {"status": "ok"}
            status, _, _ = _raw(server.port, _post("/healthz", b"{}"))
            assert status == 405
            status, _, _ = _raw(server.port, _post("/readyz", b"{}"))
            assert status == 405
        finally:
            server.shutdown()

    def test_flap_counter_moves_on_transitions(self):
        counters = CounterSet()
        probe = health.ReadinessProbe(counters=counters)
        flag = {"ok": True}
        probe.register("cond", lambda: (flag["ok"], ""))
        probe.evaluate()
        assert counters.get("pas_ready", kind="gauge") == 1
        assert counters.get("pas_ready_transitions_total") == 0
        flag["ok"] = False
        probe.evaluate()
        assert counters.get("pas_ready", kind="gauge") == 0
        assert counters.get("pas_ready_transitions_total") == 1
        probe.evaluate()  # steady state: no extra flap
        assert counters.get("pas_ready_transitions_total") == 1
        flag["ok"] = True
        probe.evaluate()
        assert counters.get("pas_ready_transitions_total") == 2

    def test_raising_condition_fails_closed(self):
        probe = health.ReadinessProbe(counters=CounterSet())

        def broken():
            raise RuntimeError("boom")

        probe.register("broken", broken)
        ready, results = probe.evaluate()
        assert ready is False
        assert "boom" in results[0]["reason"]

    def test_empty_probe_is_ready(self):
        status, body = health.ReadinessProbe(
            counters=CounterSet()
        ).readyz_response()
        assert status == 200
        assert json.loads(body)["ready"] is True

    def test_raising_conditions_provider_fails_closed(self):
        """A readiness_conditions() provider that raises must NOT yield
        an empty always-ready probe — /readyz reports 503 with the
        provider failure as the reason."""

        class Broken:
            def readiness_conditions(self):
                raise AttributeError("no freshness surface")

        probe = health.probe_for(Broken(), counters=CounterSet())
        status, body = probe.readyz_response()
        assert status == 503
        payload = json.loads(body)
        assert payload["ready"] is False
        assert "provider raised" in payload["conditions"][0]["reason"]


class TestRealExtenderReadiness:
    def test_warm_extender_with_static_cache_is_ready(self):
        """The bench/service assembly: device fastpath warmed at
        construction, seed cache with no refresh loop -> ready on both
        extender conditions."""
        ext, _names = build_extender(32, device=True)
        server = _start_threaded(ext)
        try:
            status, body = _readyz(server.port)
            assert status == 200, body
            names = {c["name"] for c in body["conditions"]}
            assert {"kernels_warmed", "telemetry_fresh"} <= names
        finally:
            server.shutdown()

    def test_registered_informer_condition_gates_readiness(self):
        class FakeInformer:
            synced = False

            def has_synced(self):
                return self.synced

        ext, _names = build_extender(32, device=True)
        informer = FakeInformer()
        server = Server(ext, metrics_provider=ext.metrics_text)
        server.probe.register(
            "policy_informer_synced",
            health.informer_synced(informer, "taspolicy"),
        )
        request = HTTPRequest(method="GET", path="/readyz", headers={}, body=b"")
        response = server.route(request)
        assert response.status == 503
        assert b"taspolicy" in response.body
        informer.synced = True
        assert server.route(request).status == 200


class TestTelemetryFreshness:
    def _store(self):
        return {"m1": {"node-a": NodeMetric(value=Quantity(5))}}

    def test_static_cache_is_fresh(self):
        cache = AutoUpdatingCache(counters=CounterSet())
        ok, reason = cache.telemetry_freshness()
        assert ok and "static" in reason

    def test_refresh_loop_lifecycle(self):
        """Unsynced -> not ready; refreshed -> fresh; loop stalled past
        the bound -> stale again (with the reason saying why)."""
        counters = CounterSet()
        cache = AutoUpdatingCache(counters=counters)
        cache.write_metric("m1", None)  # registered by a policy
        client = DummyMetricsClient(self._store())
        stop = threading.Event()
        cache.start_periodic_update(0.01, client, stop=stop)
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if cache.telemetry_freshness()[0]:
                break
            time.sleep(0.005)
        ok, reason = cache.telemetry_freshness()
        assert ok, reason
        assert counters.get("pas_telemetry_refresh_total") >= 1
        assert (
            counters.get(
                "pas_telemetry_metric_age_seconds",
                kind="gauge",
                labels={"metric": "m1"},
            )
            >= 0
        )
        # stall the loop; freshness decays past the bound
        stop.set()
        cache.freshness_max_age_s = 0.05
        time.sleep(0.15)
        ok, reason = cache.telemetry_freshness()
        assert not ok
        assert "stalled" in reason or "stale" in reason

    def test_unsynced_loop_is_not_fresh(self):
        cache = AutoUpdatingCache(counters=CounterSet())
        cache._refresh_period = 5.0  # configured but never ran a pass
        ok, reason = cache.telemetry_freshness()
        assert not ok and "refresh pass" in reason

    def test_failing_metric_counts_errors_and_goes_stale(self):
        counters = CounterSet()
        cache = AutoUpdatingCache(counters=counters)
        cache.write_metric("m1", None)
        client = DummyMetricsClient({})  # fetch always fails
        cache._refresh_period = 0.01
        cache.update_all_metrics(client)
        assert counters.get("pas_telemetry_refresh_errors_total") == 1
        ok, reason = cache.telemetry_freshness()
        assert not ok and "m1" in reason


class TestBypassUnderSaturation:
    def test_health_endpoints_readable_when_queue_saturated(self):
        """ISSUE 3 acceptance: /healthz, /readyz, and /debug/profile stay
        readable while the async admission queue is saturated — and
        /readyz reports the saturation as the failing condition."""

        class Blocking:
            release = threading.Event()

            def prioritize(self, request):
                Blocking.release.wait(15)
                return HTTPResponse.json(b"[]\n")

            filter = prioritize

            def bind(self, request):
                return HTTPResponse(status=404)

            def metrics_text(self):
                return ""

        server = _start_async(
            Blocking(), window_s=0.0, max_batch=1, max_queue_depth=1
        )
        blockers = []
        try:
            blockers = [
                threading.Thread(
                    target=lambda: _raw(
                        server.port, _post("/scheduler/prioritize", b"{}")
                    )
                )
                for _ in range(2)
            ]
            for thread in blockers:
                thread.start()
                time.sleep(0.05)
            time.sleep(0.1)
            status, _headers, payload = _get(server.port, "/healthz")
            assert status == 200
            status, body = _readyz(server.port)
            assert status == 503
            failing = {c["name"] for c in body["conditions"] if not c["ok"]}
            assert failing == {"admission_queue"}
            # /debug/profile responds too (fake tracers: no real capture)
            from platform_aware_scheduling_tpu.utils import devicewatch

            original = devicewatch._profiler_tracers
            devicewatch._profiler_tracers = lambda: (
                lambda _dir: None,
                lambda: None,
            )
            try:
                status, _headers, payload = _get(
                    server.port, "/debug/profile?ms=1"
                )
                assert status == 200
                assert "path" in json.loads(payload)
            finally:
                devicewatch._profiler_tracers = original
            # queue drains -> ready again
            Blocking.release.set()
            for thread in blockers:
                thread.join(20)
            blockers = []
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                status, _body = _readyz(server.port)
                if status == 200:
                    break
                time.sleep(0.02)
            assert status == 200
        finally:
            Blocking.release.set()
            for thread in blockers:
                thread.join(20)
            server.shutdown()


class TestLogTraceCorrelation:
    def test_structured_lines_carry_request_id(self):
        """A klog structured line emitted inside a verb handler carries
        the request's X-Request-ID, so /debug/traces entries join
        against the logs (ISSUE 3 satellite)."""
        records = []

        class Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = Capture()
        logging.getLogger("pas_tpu").addHandler(handler)
        old_verbosity = klog.verbosity()
        klog.set_verbosity(2)
        ext, names = build_extender(32, device=True)
        server = _start_threaded(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            status, _, _ = _raw(
                server.port,
                _post(
                    "/scheduler/filter", body,
                    extra="X-Request-ID: log-join-1\r\n",
                ),
            )
            assert status == 200
            joined = [m for m in records if 'request_id="log-join-1"' in m]
            assert joined, records[-5:]
        finally:
            klog.set_verbosity(old_verbosity)
            logging.getLogger("pas_tpu").removeHandler(handler)
            server.shutdown()

    def test_request_context_scopes_and_restores(self):
        assert klog.current_request_id() == ""
        with klog.request_context("abc"):
            assert klog.current_request_id() == "abc"
            with klog.request_context(""):
                assert klog.current_request_id() == ""
        assert klog.current_request_id() == ""

    def test_structured_values_escape_injection(self):
        """A client-controlled X-Request-ID cannot forge structured
        fields: quotes/newlines in values are escaped in the line."""
        with klog.request_context('x" component="forged'):
            line = klog._fmt("msg", {})
        assert 'component="forged' not in line
        assert '\\"' in line

"""Interned node-name universes (ISSUE 11): the wire-path repeat-request
floor.

Three layers are pinned here, each against the byte-comparability
discipline (PR-6/PR-7): (1) the C surface — UniverseCache digest+memcmp
keying, second-sighting interning, MRU eviction, and the universe-backed
encoders (``filter_respond`` / ``select_encode_universe``) producing
bytes identical to the per-request encoders; (2) the verb matrix —
warm (interned/spliced) responses byte-equal to the exact Python path
across native/host policies, threaded/async front-ends, gang on/off and
forecast on/off, including invalidation on node add/remove/rename,
metric-state change, and gang-reservation-version change (no
stale-universe splice, ever); (3) the off path — with the universe
cache disabled the wire is byte-identical to the pre-universe paths.

This file also runs under ``make test-wirec`` (ASan+UBSan over the
instrumented extension) — the refcount/ownership coverage for the cache
the C surface grew."""

import json

import numpy as np
import pytest

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.tas import telemetryscheduler
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.utils import labels as shared_labels
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.quantity import Quantity

from wirehelpers import post_bytes, raw_request, start_async, start_threaded

wirec = get_wirec()
pytestmark = pytest.mark.skipif(
    wirec is None or not hasattr(wirec, "UniverseCache"),
    reason="native universe support unavailable (no C toolchain)",
)


def req(body: bytes, path: str = "/scheduler/filter") -> HTTPRequest:
    return HTTPRequest(
        method="POST",
        path=path,
        headers={"Content-Type": "application/json"},
        body=body,
    )


def nn_body(names, pod="p", label="load-pol", namespace="default") -> bytes:
    metadata = {"name": pod, "namespace": namespace}
    if label is not None:
        metadata["labels"] = {"telemetry-policy": label}
    return json.dumps(
        {"Pod": {"metadata": metadata}, "NodeNames": list(names)}
    ).encode()


def exact_bytes(ext, body: bytes, path: str, monkeypatch):
    """(status, body) from the exact Python path — the native scanner
    patched away exactly like the differential fuzzer does."""
    with monkeypatch.context() as m:
        m.setattr(telemetryscheduler, "get_wirec", lambda: None)
        verb = ext.filter if path.endswith("filter") else ext.prioritize
        resp = verb(req(body, path))
    return resp.status, resp.body


def warm(ext, bodies, path: str = "/scheduler/filter", times: int = 4):
    """Drive the same-span bodies until the universe is interned and the
    skeleton seeded (1st sights, 2nd interns + promotes, 3rd splices)."""
    verb = ext.filter if path.endswith("filter") else ext.prioritize
    last = None
    for i in range(times):
        last = verb(req(bodies[i % len(bodies)], path))
    return last


class TestCSurface:
    def _parsed(self, names):
        return wirec.parse_prioritize(nn_body(names))

    def test_second_sighting_interns(self):
        cache = wirec.UniverseCache(capacity=4)
        parsed = self._parsed(["a", "b"])
        assert cache.lookup(parsed, True) is None
        assert cache.note_seen(parsed, True) is False  # first sighting
        assert cache.note_seen(parsed, True) is True  # second: intern now
        universe, evicted = cache.intern(parsed, True)
        assert evicted == 0
        assert universe.num == 2
        hit = cache.lookup(self._parsed(["a", "b"]), True)
        assert hit is not None and hit.uid == universe.uid
        assert cache.occupancy == 1

    def test_same_length_different_content_misses(self):
        """The stale-splice guard: a span of identical LENGTH but
        different bytes (a renamed node) must never hit."""
        cache = wirec.UniverseCache(capacity=4)
        parsed = self._parsed(["node-1", "node-2"])
        cache.note_seen(parsed, True)
        cache.intern(parsed, True)
        assert cache.lookup(self._parsed(["node-1", "node-3"]), True) is None
        assert cache.lookup(self._parsed(["node-1", "node-2"]), True) is not None

    def test_eviction_bound_and_count(self):
        cache = wirec.UniverseCache(capacity=2)
        kept = []
        for i in range(4):
            parsed = self._parsed([f"n{i}", f"m{i}"])
            universe, evicted = cache.intern(parsed, True)
            kept.append(universe)
            assert evicted == (1 if i >= 2 else 0)
        assert cache.occupancy == 2
        # evicted universes stay valid for holders (refcounted, not freed)
        assert kept[0].names() == ("n0", "m0")
        assert [u["names"] for u in cache.universes()] == [2, 2]

    @pytest.mark.parametrize("case", range(4))
    def test_filter_respond_matches_filter_encode(self, case):
        rng = np.random.default_rng(case)
        names = [f"node-{i}" for i in range(40)]
        if case >= 1:
            names[3] = "weird é中"  # non-ASCII: pre-encoded path
            names[7] = 'esc"aped\\name'
        if case >= 2:
            names[9] = names[4]  # duplicate -> FailedNodes dedup
            names[11] = ""  # empty name
        table = wirec.build_table([n for n in names if n != "ghost"][:32])
        body = nn_body(names)
        parsed = wirec.parse_prioritize(body)
        cache = wirec.UniverseCache(capacity=2)
        universe, _ = cache.intern(parsed, True)
        mask = (rng.random(32) < 0.4).astype(np.uint8).tobytes()
        reasons = [
            json.dumps(f"r{i}").encode() if i % 3 == 0 else None
            for i in range(32)
        ]
        for reason_arg in (None, reasons):
            if reason_arg is None:
                want = wirec.filter_encode(parsed, table, mask)
                got = wirec.filter_respond(universe, table, mask)
            else:
                want = wirec.filter_encode(parsed, table, mask, reason_arg)
                got = wirec.filter_respond(universe, table, mask, reason_arg)
            assert got == want  # (bytes, n_failed) both

    def test_select_encode_universe_matches_select_encode(self):
        names = [f"node-{i}" for i in range(30)]
        names[5] = "uniçode"
        table = wirec.build_table(names[:25])
        body = nn_body(names)
        parsed = wirec.parse_prioritize(body)
        universe, _ = wirec.UniverseCache().intern(parsed, True)
        ranked = np.random.default_rng(0).permutation(25).astype(np.int64)
        for planned in (-1, 7):
            want = wirec.select_encode(parsed, table, ranked, planned, True)
            got = wirec.select_encode_universe(universe, table, ranked, planned)
            assert got == want

    def test_rows_rebuild_on_table_change(self):
        """Node interning moved (a node joined): the universe's cached
        row map must rebuild against the new table, not splice stale
        rows."""
        names = ["a", "b", "c"]
        parsed = wirec.parse_prioritize(nn_body(names))
        universe, _ = wirec.UniverseCache().intern(parsed, True)
        t1 = wirec.build_table(["a", "b", "c"])
        t2 = wirec.build_table(["z", "a", "b", "c"])  # rows shifted by 1
        mask1 = bytes([1, 0, 0])
        assert wirec.filter_respond(universe, t1, mask1) == (
            wirec.filter_encode(parsed, t1, mask1)
        )
        mask2 = bytes([0, 1, 0, 0])  # "a" violates in t2's numbering
        assert wirec.filter_respond(universe, t2, mask2) == (
            wirec.filter_encode(parsed, t2, mask2)
        )

    def test_filter_respond_rejects_nodes_universe(self):
        body = json.dumps(
            {
                "Pod": {"metadata": {}},
                "Nodes": {"items": [{"metadata": {"name": "a"}}]},
            }
        ).encode()
        parsed = wirec.parse_prioritize(body)
        universe, _ = wirec.UniverseCache().intern(parsed, False)
        table = wirec.build_table(["a"])
        with pytest.raises(ValueError):
            wirec.filter_respond(universe, table, b"\x00")

    def test_names_tuple_matches_materialized_list(self):
        names = ["plain", "", "uniç中", 'q"uote\\x', "plain"]
        parsed = wirec.parse_prioritize(nn_body(names))
        universe, _ = wirec.UniverseCache().intern(parsed, True)
        assert list(universe.names()) == parsed.node_names_list() == names
        assert universe.names() is universe.names()  # built once, shared


class _StubGangs:
    """The tracker surface the Filter cache path consumes, with a
    controllable reservation version — reason strings come from the
    SAME shared helper the real tracker and fastpath.gang_merged use,
    so the exact-path overlay and the cached merge stay byte-equal."""

    def __init__(self):
        self.version = 1
        self.held = {}

    def cache_token(self):
        return self.version, dict(self.held)

    def filter_overlay(self, pod, clean):
        failed = {
            node: shared_labels.gang_reserved_reason(gang_id)
            for node, gang_id in self.held.items()
            if node in clean
        }
        return failed, {}

    def prioritize_overlay(self, pod, names):
        return None


class TestVerbParityMatrix:
    NUM = 48

    def _assert_warm_equals_exact(
        self, ext, bodies, path, monkeypatch, times=5
    ):
        status, want = exact_bytes(ext, bodies[0], path, monkeypatch)
        verb = ext.filter if path.endswith("filter") else ext.prioritize
        for i in range(times):
            resp = verb(req(bodies[i % len(bodies)], path))
            assert resp.status == status
            assert resp.body == want, f"request {i} diverged from exact"
        return want

    @pytest.mark.parametrize("path", [
        "/scheduler/filter", "/scheduler/prioritize",
    ])
    def test_warm_equals_exact_device(self, path, monkeypatch):
        ext, names = build_extender(self.NUM, device=True)
        bodies = make_bodies(names, "nodenames")
        before = trace.COUNTERS.get("pas_wire_intern_hits_total")
        self._assert_warm_equals_exact(ext, bodies, path, monkeypatch)
        assert trace.COUNTERS.get("pas_wire_intern_hits_total") > before

    def test_warm_equals_exact_nodes_mode_prioritize(self, monkeypatch):
        ext, names = build_extender(self.NUM, device=True)
        bodies = make_bodies(names, "nodes")
        self._assert_warm_equals_exact(
            ext, bodies, "/scheduler/prioritize", monkeypatch
        )

    def test_warm_equals_exact_host_only(self, monkeypatch):
        """The exact-host fallback: a host-only metric (sub-milli) keeps
        Filter AND Prioritize on exact host semantics; the interned
        universe only replaces the body decode — bytes must match the
        exact path's for both verbs."""
        ext, names = build_extender(self.NUM, device=True)
        ext.cache.write_metric(
            "load_metric",
            {
                n: NodeMetric(value=Quantity("100500u" if i % 2 else "2"))
                for i, n in enumerate(names)
            },
        )
        assert ext.mirror.metric_host_only("load_metric")
        bodies = make_bodies(names, "nodenames")
        for path in ("/scheduler/filter", "/scheduler/prioritize"):
            self._assert_warm_equals_exact(ext, bodies, path, monkeypatch)

    def test_forecast_ranking_parity(self, monkeypatch):
        ext, names = build_extender(self.NUM, device=True, forecast=True)
        bodies = make_bodies(names, "nodenames")
        self._assert_warm_equals_exact(
            ext, bodies, "/scheduler/prioritize", monkeypatch
        )

    def test_gang_version_invalidates_skeleton(self, monkeypatch):
        """A reservation change between byte-identical requests must MISS
        the skeleton (its key carries the reservation version) and serve
        the new exact verdict — never a stale splice."""
        ext, names = build_extender(self.NUM, device=True)
        ext.gangs = _StubGangs()
        bodies = make_bodies(names, "nodenames")
        path = "/scheduler/filter"
        clean = self._assert_warm_equals_exact(
            ext, bodies, path, monkeypatch
        )
        # a reservation lands: same wire bytes in, NEW verdict out
        ext.gangs.held = {names[0]: "gang-a", names[3]: "gang-a"}
        ext.gangs.version = 2
        reserved = self._assert_warm_equals_exact(
            ext, bodies, path, monkeypatch
        )
        assert reserved != clean
        assert names[0].encode() in reserved
        # released: back to the clean bytes (and still exact-equal)
        ext.gangs.held = {}
        ext.gangs.version = 3
        assert self._assert_warm_equals_exact(
            ext, bodies, path, monkeypatch
        ) == clean

    def test_node_add_remove_rename_reinterns(self, monkeypatch):
        """THE mutation pin: node add/remove/rename between requests
        must miss the universe cache and re-intern — each new candidate
        list's warm responses equal ITS exact bytes."""
        ext, names = build_extender(self.NUM, device=True)
        path = "/scheduler/filter"
        streams = [
            names,                                   # baseline
            names + ["node-extra-00001"],            # node added
            names[:-1],                              # node removed
            [n if i != 2 else "node-renamed" for i, n in enumerate(names)],
        ]
        for stream_names in streams:
            bodies = [
                nn_body(stream_names, pod=f"pod-{i}") for i in range(4)
            ]
            misses = trace.COUNTERS.get("pas_wire_intern_misses_total")
            self._assert_warm_equals_exact(ext, bodies, path, monkeypatch)
            assert (
                trace.COUNTERS.get("pas_wire_intern_misses_total") > misses
            ), "a mutated candidate list must miss the universe cache"

    def test_metric_state_change_respected_on_warm_path(self, monkeypatch):
        """Cluster-state mutation: a metric refresh that flips a node
        into violation must flow through warm (interned) requests — the
        skeleton key is the violation-set identity."""
        ext, names = build_extender(self.NUM, device=True)
        bodies = make_bodies(names, "nodenames")
        path = "/scheduler/filter"
        clean = self._assert_warm_equals_exact(ext, bodies, path, monkeypatch)
        assert b"FailedNodes\": {}" in clean
        ext.cache.write_metric(
            "load_metric",
            {
                n: NodeMetric(value=Quantity(10**10 if i == 0 else 5))
                for i, n in enumerate(names)
            },
        )
        violating = self._assert_warm_equals_exact(
            ext, bodies, path, monkeypatch
        )
        assert violating != clean
        assert names[0].encode() in violating.split(b"FailedNodes")[1]

    def test_state_change_skeletons_prewarmed(self, monkeypatch):
        """A metric refresh mints a new violation-set/ranking identity;
        the warm pass must PRE-RENDER the skeletons for every interned
        universe so the first request of the new sync window is still a
        response-cache HIT (spliced), not a re-render."""
        ext, names = build_extender(self.NUM, device=True)
        bodies = make_bodies(names, "nodenames")
        warm(ext, bodies)
        warm(ext, bodies, path="/scheduler/prioritize")
        # the refresh: same topology, shifted values -> new identities
        ext.cache.write_metric(
            "load_metric",
            {n: NodeMetric(value=Quantity(7 + i)) for i, n in enumerate(names)},
        )
        for path, counter in (
            ("/scheduler/filter", "pas_filter_cache_hit_total"),
            ("/scheduler/prioritize", "pas_fastpath_response_hit_total"),
        ):
            hits = trace.COUNTERS.get(counter)
            status, want = exact_bytes(ext, bodies[0], path, monkeypatch)
            verb = ext.filter if path.endswith("filter") else ext.prioritize
            resp = verb(req(bodies[0], path))
            assert (resp.status, resp.body) == (status, want)
            assert trace.COUNTERS.get(counter) == hits + 1, (
                f"{path}: first post-refresh request must splice a "
                f"pre-warmed skeleton"
            )

    def test_disabled_universe_wire_identical(self, monkeypatch):
        """Acceptance: with the universe cache disabled the wire is
        byte-identical to today — same stream, enabled vs disabled
        extender, every response equal."""
        ext_on, names = build_extender(self.NUM, device=True)
        ext_off, _ = build_extender(self.NUM, device=True)
        ext_off.fastpath.UNIVERSE_CACHE_SIZE = 0  # --off analog
        for path in ("/scheduler/filter", "/scheduler/prioritize"):
            bodies = make_bodies(names, "nodenames")
            verb_on = (
                ext_on.filter if path.endswith("filter") else ext_on.prioritize
            )
            verb_off = (
                ext_off.filter
                if path.endswith("filter")
                else ext_off.prioritize
            )
            for i in range(5):
                body = bodies[i % len(bodies)]
                a = verb_on(req(body, path))
                b = verb_off(req(body, path))
                assert (a.status, a.body) == (b.status, b.body)
        assert ext_off.fastpath._universes in (None, False)

    def test_universe_cache_size_env_parsing(self, monkeypatch):
        from platform_aware_scheduling_tpu.tas.fastpath import (
            _universe_cache_size,
        )

        monkeypatch.setenv("PAS_TPU_UNIVERSE_CACHE", "16")
        assert _universe_cache_size() == 16
        monkeypatch.setenv("PAS_TPU_UNIVERSE_CACHE", "0")
        assert _universe_cache_size() == 0
        monkeypatch.setenv("PAS_TPU_UNIVERSE_CACHE", "junk")
        assert _universe_cache_size() == 8
        monkeypatch.setenv("PAS_TPU_UNIVERSE_CACHE", "-3")
        assert _universe_cache_size() == 8


class TestFrontEndParity:
    """Warm (spliced) responses over REAL sockets: threaded and async
    front-ends serve byte-identical bodies for the same stream, equal to
    the exact in-process bytes."""

    @pytest.mark.parametrize("path", [
        "/scheduler/filter", "/scheduler/prioritize",
    ])
    def test_threaded_async_byte_equal(self, path, monkeypatch):
        ext_t, names = build_extender(32, device=True)
        ext_a, _ = build_extender(32, device=True)
        status, want = exact_bytes(
            ext_t, make_bodies(names, "nodenames")[0], path, monkeypatch
        )
        threaded = start_threaded(ext_t)
        async_server = start_async(ext_a)
        try:
            bodies = make_bodies(names, "nodenames")
            for i in range(5):
                body = bodies[i % len(bodies)]
                for server in (threaded, async_server):
                    got_status, _h, got = raw_request(
                        server.port, post_bytes(path, body)
                    )
                    assert got_status == status
                    assert got == want
        finally:
            threaded.shutdown()
            async_server.shutdown()


class TestDebugWire:
    def test_404_without_fastpath(self):
        ext, _names = build_extender(8, device=False)
        server = start_threaded(ext)
        try:
            status, _h, body = raw_request(
                server.port,
                (
                    b"GET /debug/wire HTTP/1.1\r\nHost: t\r\n"
                    b"Connection: close\r\n\r\n"
                ),
            )
            assert status == 404
            assert b"error" in body
        finally:
            server.shutdown()

    def test_payload_reflects_interning(self):
        ext, names = build_extender(8, device=True)
        bodies = make_bodies(names, "nodenames")
        warm(ext, bodies)
        payload = ext.fastpath.wire_debug()
        assert payload["enabled"] is True
        assert payload["occupancy"] == 1
        assert payload["capacity"] >= 1
        assert payload["universes"][0]["kind"] == "nodenames"
        assert payload["universes"][0]["names"] == 8
        assert payload["skeletons"]["filter"], "warm filter must splice"
        assert payload["counters"]["hits"] >= 1
        json.dumps(payload)  # wire-serializable as served by /debug/wire

    def test_405_non_get(self):
        ext, _names = build_extender(8, device=True)
        server = start_threaded(ext)
        try:
            status, _h, _b = raw_request(
                server.port, post_bytes("/debug/wire", b"{}")
            )
            assert status == 405
        finally:
            server.shutdown()

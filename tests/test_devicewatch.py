"""Device & compile visibility (utils/devicewatch.py): memory watermark
gauges (graceful no-op on CPU, real gauges against fake devices),
one-shot cost-analysis capture (direct + via the first-compile hook),
and the bounded /debug/profile capture over both front-ends."""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from platform_aware_scheduling_tpu.utils import devicewatch, trace
from platform_aware_scheduling_tpu.utils.tracing import CounterSet


class FakeDevice:
    def __init__(self, device_id, stats):
        self.id = device_id
        self._stats = stats

    def memory_stats(self):
        return self._stats


class TestDeviceWatcher:
    def test_cpu_sample_is_a_clean_noop(self):
        counters = CounterSet()
        watcher = devicewatch.DeviceWatcher(counters=counters)
        watcher.sample()  # CPU devices report no stats -> no gauges, no raise
        text = counters.prometheus_text()
        assert "pas_device_memory" not in text

    def test_fake_devices_export_watermarks(self, monkeypatch):
        counters = CounterSet()
        devices = [
            FakeDevice(0, {"bytes_in_use": 100, "peak_bytes_in_use": 200,
                           "bytes_limit": 1000}),
            FakeDevice(1, {"bytes_in_use": 50}),
            FakeDevice(2, None),  # backend without stats: skipped
        ]
        monkeypatch.setattr(jax, "local_devices", lambda: devices)
        watcher = devicewatch.DeviceWatcher(counters=counters)
        assert watcher.sample() == 2
        assert counters.get(
            "pas_device_memory_in_use_bytes", labels={"device": "0"}
        ) == 100
        assert counters.get(
            "pas_device_memory_peak_bytes", labels={"device": "0"}
        ) == 200
        assert counters.get(
            "pas_device_memory_limit_bytes", labels={"device": "0"}
        ) == 1000
        assert counters.get(
            "pas_device_memory_in_use_bytes", labels={"device": "1"}
        ) == 50
        # the exposition parses and stays inside the declared inventory
        families = trace.parse_prometheus_text(counters.prometheus_text())
        for family in families:
            assert family in trace.METRICS


class TestKernelCostCapture:
    def test_direct_capture_exports_flops_and_dedupes(self):
        counters = CounterSet()
        fn = jax.jit(lambda x: x @ x)
        x = jnp.ones((8, 8), dtype=jnp.float32)
        fn(x)
        captured = devicewatch.capture_kernel_cost(
            "cost_toy_kernel", fn, (x,), counters=counters
        )
        assert captured, "CPU backend supports cost_analysis"
        flops = counters.get(
            "pas_device_kernel_flops", labels={"kernel": "cost_toy_kernel"}
        )
        assert flops > 0
        # second capture for the same kernel name is a no-op
        assert not devicewatch.capture_kernel_cost(
            "cost_toy_kernel", fn, (x,), counters=counters
        )

    def test_first_compile_hook_captures_watched_kernel(self):
        counters = CounterSet()
        hook = devicewatch.install_cost_hooks(counters=counters)
        try:
            watched = trace.watch_jit(
                "cost_hooked_kernel",
                jax.jit(lambda x: jnp.sum(x * 2.0)),
                CounterSet(),
            )
            watched(jnp.ones((16,), dtype=jnp.float32))
            assert counters.get(
                "pas_device_kernel_flops",
                labels={"kernel": "cost_hooked_kernel"},
            ) > 0
        finally:
            trace.FIRST_COMPILE_HOOKS.remove(hook)


class TestProfileCapture:
    def test_capture_returns_trace_dir(self):
        status, body = devicewatch.profile_response("/debug/profile?ms=1")
        payload = json.loads(body)
        if status == 404:  # profiler genuinely unavailable on this build
            assert "error" in payload
            return
        assert status == 200
        assert os.path.isdir(payload["path"])
        assert payload["ms"] == 1

    def test_bad_ms_is_400(self):
        status, body = devicewatch.profile_response("/debug/profile?ms=nope")
        assert status == 400

    def test_unavailable_profiler_is_404(self, monkeypatch):
        monkeypatch.setattr(devicewatch, "_profiler_tracers", lambda: None)
        status, body = devicewatch.profile_response("/debug/profile?ms=5")
        assert status == 404
        assert "unavailable" in json.loads(body)["error"]

    def test_ms_is_clamped(self, monkeypatch):
        slept = {}
        monkeypatch.setattr(
            devicewatch, "_profiler_tracers",
            lambda: (lambda _dir: None, lambda: None),
        )
        monkeypatch.setattr(
            devicewatch.time, "sleep", lambda s: slept.setdefault("s", s)
        )
        status, body = devicewatch.profile_response(
            "/debug/profile?ms=999999999"
        )
        assert status == 200
        assert json.loads(body)["ms"] == devicewatch.PROFILE_MAX_MS
        assert slept["s"] == devicewatch.PROFILE_MAX_MS / 1000.0

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_endpoint_over_the_wire(self, serving, monkeypatch):
        from benchmarks.http_load import build_extender
        from wirehelpers import (
            get_request as _get,
            post_bytes as _post,
            raw_request as _raw,
            start_async as _start_async,
            start_threaded as _start_threaded,
        )

        monkeypatch.setattr(
            devicewatch, "_profiler_tracers",
            lambda: (lambda _dir: None, lambda: None),
        )
        ext, _names = build_extender(32, device=True)
        server = (
            _start_threaded(ext) if serving == "threaded"
            else _start_async(ext)
        )
        try:
            status, _headers, payload = _get(server.port, "/debug/profile?ms=1")
            assert status == 200
            assert "path" in json.loads(payload)
            # GET-only, like the other observability endpoints
            status, _, _ = _raw(server.port, _post("/debug/profile", b"{}"))
            assert status == 405
        finally:
            server.shutdown()

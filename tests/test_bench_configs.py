"""benchmarks/configs.py — the BASELINE config benches must run and agree
with host semantics at tiny shapes (the full shapes run in bench.py on
real hardware; these tests pin correctness, not performance)."""

from benchmarks import configs


class TestConfigBenches:
    def test_config1_runs_and_reports(self):
        out = configs.config1_single_metric(num_nodes=3)
        assert out["device_p99_ms"] > 0
        assert out["control_p99_ms"] > 0
        assert "speedup_p99" in out

    def test_config2_runs_and_reports(self):
        out = configs.config2_multi_metric(num_nodes=64, num_pods=8)
        assert out["device_ms_per_solve"] > 0
        assert out["control_ms_per_solve"] > 0
        assert "speedup" in out

    def test_config3_parity_small(self):
        out = configs.config3_gas_binpack(num_nodes=16, num_cards=4)
        assert out["parity"] is True
        assert 0 <= out["nodes_fitting"] <= 16

    def test_config3_parity_default_shape(self):
        out = configs.config3_gas_binpack()
        assert out["parity"] is True

    def test_config5_runs(self):
        out = configs.config5_churn(num_nodes=128, num_pods=8, ticks=2)
        assert out["device_ms_per_tick"] > 0
        assert out["control_ms_per_tick"] > 0

    def test_host_first_fit_rejects_when_full(self):
        import numpy as np

        state, request, max_gpus, hosts = configs._binpack_problem(
            num_nodes=4, num_cards=2
        )
        hosts["used"] = np.broadcast_to(
            hosts["cap"][:, None, :], hosts["used"].shape
        ).copy()  # every card already at capacity
        fits = configs._host_first_fit(hosts)
        assert not fits.any()

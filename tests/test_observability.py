"""The tracing layer over live sockets (docs/observability.md):
X-Request-ID echo (including 503 backpressure), span parity between the
threaded and async front-ends, batch spans linking their members,
/debug/traces boundedness, /metrics as valid Prometheus exposition, the
stage-sum-vs-end-to-end accounting bar, and the JAX retrace counter
under a shape-varying request sequence.

Everything is hermetic: in-process servers on 127.0.0.1 ephemeral ports,
small synthetic clusters seeded like benchmarks/http_load.
"""

import json
import threading
import time

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
)
from platform_aware_scheduling_tpu.utils import trace
from wirehelpers import (
    get_request as _get,
    post_bytes as _post,
    raw_request as _raw,
    start_async as _start_async,
    start_threaded as _start_threaded,
)

HANDLER_STAGES = {"decode", "kernel", "encode"}


def _wait_for_span(trace_id: str, timeout: float = 5.0):
    """The span lands in TRACES after the response bytes are written;
    poll briefly so readers never race the writer."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        span = trace.TRACES.find(trace_id)
        if span is not None:
            return span
        time.sleep(0.005)
    raise AssertionError(f"span {trace_id} never recorded")


class TestRequestIdEcho:
    def test_threaded_echoes_provided_id(self):
        ext, names = build_extender(48, device=True)
        server = _start_threaded(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            status, headers, _ = _raw(
                server.port,
                _post(
                    "/scheduler/prioritize", body,
                    extra="X-Request-ID: tid-echo-1\r\n",
                ),
            )
            assert status == 200
            assert headers["x-request-id"] == "tid-echo-1"
            # absent header -> a generated id comes back
            status, headers, _ = _raw(
                server.port, _post("/scheduler/prioritize", body)
            )
            assert status == 200
            assert len(headers["x-request-id"]) == 32
            # non-verb responses carry it too (404 catch-all)
            status, headers, _ = _raw(server.port, _post("/nope", b"{}"))
            assert status == 404
            assert headers["x-request-id"]
        finally:
            server.shutdown()

    def test_async_echoes_on_503_backpressure(self):
        """The 503 shed at a saturated admission queue still carries the
        caller's X-Request-ID (and Retry-After)."""

        class Blocking:
            release = threading.Event()

            def prioritize(self, request):
                Blocking.release.wait(15)
                return HTTPResponse.json(b"[]\n")

            filter = prioritize

            def bind(self, request):
                return HTTPResponse(status=404)

        server = _start_async(
            Blocking(), window_s=0.0, max_batch=1, max_queue_depth=1
        )
        try:
            n = 5
            results = [None] * n

            def client(i):
                results[i] = _raw(
                    server.port,
                    _post(
                        "/scheduler/prioritize", b"{}",
                        extra=f"X-Request-ID: shed-{i}\r\n",
                    ),
                )

            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
                time.sleep(0.05)
            time.sleep(0.2)
            Blocking.release.set()
            for t in threads:
                t.join(20)
            statuses = [r[0] for r in results]
            assert 503 in statuses and 200 in statuses
            for i, (status, headers, _) in enumerate(results):
                assert headers["x-request-id"] == f"shed-{i}", status
                if status == 503:
                    assert "retry-after" in headers
                    span = _wait_for_span(f"shed-{i}")
                    assert span.attrs.get("rejected") is True
        finally:
            server.shutdown()


class TestSpanParity:
    def test_same_request_same_handler_stages_both_paths(self):
        """One request shape through the threaded and the async front-end
        produces spans with the SAME handler stages and path attribution —
        the trace vocabulary must not depend on the front-end."""
        ext_t, names = build_extender(64, device=True, seed=3)
        ext_a, _ = build_extender(64, device=True, seed=3)
        body = make_bodies(names, "nodenames", count=1)[0]
        threaded = _start_threaded(ext_t)
        try:
            status, _, t_body = _raw(
                threaded.port,
                _post(
                    "/scheduler/prioritize", body,
                    extra="X-Request-ID: parity-t\r\n",
                ),
            )
            assert status == 200
        finally:
            threaded.shutdown()
        asynchronous = _start_async(ext_a)
        try:
            status, _, a_body = _raw(
                asynchronous.port,
                _post(
                    "/scheduler/prioritize", body,
                    extra="X-Request-ID: parity-a\r\n",
                ),
            )
            assert status == 200
        finally:
            asynchronous.shutdown()
        assert t_body == a_body  # wire parity, as pinned by test_serving
        span_t = _wait_for_span("parity-t")
        span_a = _wait_for_span("parity-a")
        stages_t = {name for name, _, _ in span_t.stages}
        stages_a = {name for name, _, _ in span_a.stages}
        # identical handler-stage vocabulary...
        assert stages_t & HANDLER_STAGES == stages_a & HANDLER_STAGES
        assert "decode" in stages_t
        # ...identical attribution...
        assert span_t.attrs.get("verb") == span_a.attrs.get("verb")
        assert span_t.attrs.get("path") == span_a.attrs.get("path")
        # ...and the async extras are exactly the dispatch stages
        assert "queue_wait" in stages_a and "coalesce" in stages_a
        assert "queue_wait" not in stages_t

    def test_batch_span_links_n_request_spans(self):
        """N requests coalesced into one batch -> ONE serving_batch span
        linking all N member trace ids, each member pointing back."""
        n = 5
        ext, names = build_extender(96, device=True)
        server = _start_async(ext, window_s=0.25, max_batch=64)
        try:
            bodies = make_bodies(names, "nodenames", count=n)
            _raw(
                server.port, _post("/scheduler/prioritize", bodies[0])
            )  # warm: connection setup + caches
            barrier = threading.Barrier(n)
            errors = []

            def client(i):
                try:
                    barrier.wait(5)
                    status, _, _ = _raw(
                        server.port,
                        _post(
                            "/scheduler/prioritize", bodies[i],
                            extra=f"X-Request-ID: member-{i}\r\n",
                        ),
                    )
                    assert status == 200
                except Exception as exc:  # pragma: no cover - diagnostics
                    errors.append(exc)

            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(20)
            assert not errors
            member_ids = {f"member-{i}" for i in range(n)}
            spans = [_wait_for_span(tid) for tid in sorted(member_ids)]
            batch_ids = {s.attrs.get("batch_id") for s in spans}
            assert len(batch_ids) == 1, "all members share one batch"
            snapshot = trace.TRACES.snapshot()
            batch = [
                entry
                for entry in snapshot["recent"]
                if entry["name"] == "serving_batch"
                and entry["id"] in batch_ids
            ]
            assert batch, "the batch span itself is recorded"
            assert member_ids <= set(batch[0]["links"])
            assert batch[0]["attrs"]["size"] >= n
            stage_names = {s["name"] for s in batch[0]["stages"]}
            assert {"coalesce", "batch_solve"} <= stage_names
        finally:
            server.shutdown()


class TestDebugTraces:
    def test_bounded_and_json(self, monkeypatch):
        """/debug/traces stays bounded no matter how many requests flow:
        recent <= capacity, slowest <= slow_capacity."""
        monkeypatch.setattr(
            trace, "TRACES", trace.TraceBuffer(capacity=8, slow_capacity=4)
        )
        ext, names = build_extender(48, device=True)
        server = _start_threaded(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            for _ in range(25):
                _raw(server.port, _post("/scheduler/prioritize", body))
            status, _, payload = _get(server.port, "/debug/traces")
            assert status == 200
            data = json.loads(payload)
            assert len(data["recent"]) <= 8
            assert len(data["slowest"]) <= 4
            assert data["capacity"] == 8
            # entries carry the span vocabulary
            entry = data["recent"][-1]
            assert entry["duration_ms"] > 0
            assert {s["name"] for s in entry["stages"]} & HANDLER_STAGES
            # non-GET is rejected
            status, _, _ = _raw(server.port, _post("/debug/traces", b"{}"))
            assert status == 405
        finally:
            server.shutdown()


class TestObservabilityUnderLoad:
    def test_debug_endpoints_bypass_saturated_queue(self):
        """GET /debug/traces and /metrics stay readable while the
        admission queue is saturated — the diagnostic surface must work
        exactly when the condition it diagnoses is happening."""

        class Blocking:
            release = threading.Event()

            def prioritize(self, request):
                Blocking.release.wait(15)
                return HTTPResponse.json(b"[]\n")

            filter = prioritize

            def bind(self, request):
                return HTTPResponse(status=404)

        server = _start_async(
            Blocking(), window_s=0.0, max_batch=1, max_queue_depth=1
        )
        try:
            # saturate: one request blocks the solver, one fills the queue
            blockers = [
                threading.Thread(
                    target=lambda: _raw(
                        server.port, _post("/scheduler/prioritize", b"{}")
                    )
                )
                for _ in range(2)
            ]
            for t in blockers:
                t.start()
                time.sleep(0.05)
            time.sleep(0.1)
            status, _, payload = _get(server.port, "/debug/traces")
            assert status == 200
            json.loads(payload)
            status, _, _ = _get(server.port, "/metrics")
            assert status == 200
        finally:
            Blocking.release.set()
            for t in blockers:
                t.join(20)
            server.shutdown()


class TestMetricsExposition:
    def test_threaded_metrics_round_trip(self):
        ext, names = build_extender(48, device=True)
        server = _start_threaded(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            _raw(server.port, _post("/scheduler/prioritize", body))
            _raw(server.port, _post("/scheduler/filter", body))
            status, _, payload = _get(server.port, "/metrics")
            assert status == 200
            families = trace.parse_prometheus_text(payload.decode())
            hist = families["pas_request_duration_seconds"]
            assert hist["type"] == "histogram"
            verbs = {
                labels.get("verb")
                for name, labels, _ in hist["samples"]
                if name.endswith("_count")
            }
            assert {"prioritize", "filter"} <= verbs
            assert families["pas_prioritize_native_total"]["type"] == "counter"
        finally:
            server.shutdown()

    def test_async_metrics_round_trip(self):
        ext, names = build_extender(48, device=True)
        server = _start_async(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            _raw(server.port, _post("/scheduler/prioritize", body))
            status, _, payload = _get(server.port, "/metrics")
            assert status == 200
            families = trace.parse_prometheus_text(payload.decode())
            hist = families["pas_request_duration_seconds"]
            assert hist["type"] == "histogram"
            verbs = {
                labels.get("verb")
                for name, labels, _ in hist["samples"]
                if name.endswith("_count")
            }
            # the extender's verb latencies and the serving stages share
            # ONE histogram family (a second family header would be
            # invalid exposition)
            assert {"prioritize", "serving_batch_solve"} <= verbs
            assert "pas_serving_requests_total" in families
        finally:
            server.shutdown()


class TestAccounting:
    def test_stage_sum_matches_end_to_end(self):
        """ISSUE 2 acceptance: one Prioritize request through the async
        path yields a trace whose queue_wait + coalesce + decode + kernel
        + encode stages sum to within 10% of the recorded end-to-end
        latency.  A generous coalescing window dominates the timeline, so
        the bar passes exactly when the stages tile the span — any
        unattributed gap would blow the 10%.  The window doubles as the
        flake budget: 10% of 0.5 s leaves ~50 ms for scheduler hiccups in
        the read/handoff/write slivers outside the five named stages."""
        ext, names = build_extender(64, device=True)
        server = _start_async(ext, window_s=0.5, max_batch=8)
        try:
            # a rotated candidate span: guaranteed response-cache MISS, so
            # decode/kernel/encode are all exercised (a hit legitimately
            # skips encode)
            body = make_bodies(names, "nodenames", rotate_span=True, count=2)[1]
            status, headers, _ = _raw(
                server.port,
                _post(
                    "/scheduler/prioritize", body,
                    extra="X-Request-ID: acct-1\r\n",
                ),
            )
            assert status == 200
            span = _wait_for_span("acct-1")
            stages = span.stage_seconds()
            for required in (
                "queue_wait", "coalesce", "decode", "kernel", "encode"
            ):
                assert required in stages, (required, sorted(stages))
            accounted = sum(
                stages[k]
                for k in ("queue_wait", "coalesce", "decode", "kernel", "encode")
            )
            total = span.duration_s
            assert total > 0
            assert abs(total - accounted) <= 0.10 * total, (
                accounted, total, stages,
            )
        finally:
            server.shutdown()

    def test_shape_varying_requests_increment_retrace_counter(self):
        """ISSUE 2 acceptance: a request sequence whose cluster grows past
        the current capacity bucket forces a kernel re-lowering, and that
        shows up on pas_jax_retrace_total — a recompile is a metric, not
        a mystery."""

        def req(body):
            return HTTPRequest(
                method="POST",
                path="/scheduler/prioritize",
                headers={"Content-Type": "application/json"},
                body=body,
            )

        before = trace.COUNTERS.get("pas_jax_retrace_total")
        ext1, names1 = build_extender(48, device=True)  # 64-node bucket
        assert ext1.prioritize(req(make_bodies(names1, "nodenames", count=1)[0])).status == 200
        # 1500 nodes -> a 2048-node capacity bucket: a shape no other
        # fixture in the suite compiles, so the ranking pass MUST re-lower
        ext2, names2 = build_extender(1500, device=True)
        assert ext2.prioritize(req(make_bodies(names2, "nodenames", count=1)[0])).status == 200
        after = trace.COUNTERS.get("pas_jax_retrace_total")
        assert after > before
        # the lowering shim also counted the compile itself
        assert trace.COUNTERS.get("pas_jax_kernel_compile_total") > 0


class TestPathAttribution:
    def test_prioritize_path_counters_partition_requests(self):
        """pas_prioritize_{native,native_host,exact}_total partition the
        verb's requests: their sum moves by exactly one per request, no
        matter which path answers (host_fallback is a separate overlap
        counter for degradation events)."""
        partition = (
            "pas_prioritize_native_total",
            "pas_prioritize_native_host_total",
            "pas_prioritize_exact_total",
        )

        def totals():
            return sum(trace.COUNTERS.get(name) for name in partition)

        ext, names = build_extender(48, device=True)
        bodies = make_bodies(names, "nodenames", count=3)
        before = totals()
        for body in bodies:
            response = ext.prioritize(
                HTTPRequest(
                    method="POST",
                    path="/scheduler/prioritize",
                    headers={"Content-Type": "application/json"},
                    body=body,
                )
            )
            assert response.status == 200
        assert totals() - before == 3

    def test_filter_cache_tier_counters_move(self):
        from platform_aware_scheduling_tpu.native import get_wirec

        ext, names = build_extender(48, device=True)
        body = make_bodies(names, "nodenames", count=1)[0]

        def req(b):
            return HTTPRequest(
                method="POST",
                path="/scheduler/filter",
                headers={"Content-Type": "application/json"},
                body=b,
            )

        tiers = (
            "pas_filter_cache_hit_total",
            "pas_filter_cache_miss_total",
            "pas_filter_cache_bypass_total",
        )

        def totals():
            return sum(trace.COUNTERS.get(name) for name in tiers)

        hit0 = trace.COUNTERS.get("pas_filter_cache_hit_total")
        bypass0 = trace.COUNTERS.get("pas_filter_cache_bypass_total")
        before = totals()
        assert ext.filter(req(body)).status == 200
        assert ext.filter(req(body)).status == 200
        # the tiers PARTITION requests: exactly one tick per request
        assert totals() - before == 2
        if get_wirec() is None:
            # no native scanner: every request is a bypass, still counted
            assert (
                trace.COUNTERS.get("pas_filter_cache_bypass_total")
                >= bypass0 + 2
            )
        else:
            # second identical request serves from the span cache
            assert trace.COUNTERS.get("pas_filter_cache_hit_total") > hit0

"""Gang & topology-aware scheduling suite (ISSUE 7, docs/gang.md).

Covers the whole new capability layer:

  * topology-feasibility kernel device<->host parity (byte-exact arrays)
    and its edge cases;
  * GangTracker reservation lifecycle on a fake clock: forming ->
    reserved -> bound -> released, TTL expiry + reclaim, competing-gang
    serialization, rejection reasons, counters;
  * verb integration: gang members Filter/Prioritize against their
    reserved slice with concrete reasons through the decision-provenance
    taxonomy, Bind promotes reservations, non-gang pods fail gang-held
    nodes;
  * the ACCEPTANCE invariant over real sockets on BOTH front-ends: two
    competing gangs on a mesh that fits them both fully bind with gang
    tracking on (zero deadlock; no member of an incomplete gang binds
    after TTL expiry), the same scenario deadlocks half-placed without
    it, and device<->host feasibility parity is byte-exact on the wire;
  * gang-atomic eviction in the rebalance actuator (never a subset).
"""

import json

import numpy as np
import pytest

from benchmarks.gang_load import (
    _bind,
    _filter_passing,
    _gang_pod_obj,
    _post,
    build_mesh_service,
    run_deadlock_ab,
)
from platform_aware_scheduling_tpu.gang import (
    GangSpec,
    GangTracker,
    STATE_BOUND,
    STATE_FORMING,
    STATE_RESERVED,
)
from platform_aware_scheduling_tpu.ops import topology
from platform_aware_scheduling_tpu.rebalance.actuator import SafeActuator
from platform_aware_scheduling_tpu.testing.builders import (
    make_gang_pod,
    make_mesh_nodes,
    make_pod,
)
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.utils import decisions, trace
from wirehelpers import get_request, post_bytes, raw_request, start_async, \
    start_threaded


# ---------------------------------------------------------------------------
# topology kernel
# ---------------------------------------------------------------------------


class TestTopologyKernel:
    def test_device_host_parity_byte_exact(self):
        rng = np.random.default_rng(7)
        for _ in range(25):
            m, n = rng.integers(1, 10, 2)
            free = rng.random((m, n)) < 0.55
            for h, w in [(1, 1), (2, 2), (2, 3), (3, 1), (int(m), int(n))]:
                device = topology.topology_feasibility_device(free, h, w)
                host = topology.topology_feasibility_host(free, h, w)
                for d_arr, h_arr in zip(device, host):
                    assert d_arr.dtype == h_arr.dtype
                    assert np.array_equal(d_arr, h_arr)

    def test_full_mesh_every_anchor_feasible(self):
        feas = topology.topology_feasibility_host(np.ones((4, 4), bool), 2, 2)
        assert feas.anchor_ok[:3, :3].all()
        assert not feas.anchor_ok[3, :].any()  # window would overflow
        assert feas.node_ok.all()

    def test_empty_mesh_nothing_feasible(self):
        feas = topology.topology_feasibility_host(np.zeros((4, 4), bool), 2, 2)
        assert not feas.anchor_ok.any()
        assert not feas.node_ok.any()
        assert topology.best_anchor(feas) is None

    def test_window_larger_than_mesh_is_infeasible(self):
        for fn in (
            topology.topology_feasibility_host,
            topology.topology_feasibility_device,
        ):
            feas = fn(np.ones((2, 2), bool), 3, 1)
            assert not feas.anchor_ok.any()

    def test_exact_fit_single_anchor(self):
        feas = topology.topology_feasibility_host(np.ones((2, 4), bool), 2, 4)
        assert np.argwhere(feas.anchor_ok).tolist() == [[0, 0]]
        # nothing outside the window remains: zero stranded fragments
        assert int(feas.anchor_score[0, 0]) == 0

    def test_best_anchor_minimizes_stranded_fragments(self):
        """On an L-shaped free region the 2x2 window snugly in the
        corner strands fewer free cells than one in the open area."""
        free = np.ones((4, 4), bool)
        free[2:, 2:] = False  # only an L remains
        feas = topology.topology_feasibility_host(free, 2, 2)
        best = topology.best_anchor(feas)
        assert best is not None
        i, j, score = best
        # every feasible anchor's score is >= the winner's
        scores = feas.anchor_score[feas.anchor_ok]
        assert score == int(scores.min())

    def test_node_score_is_min_over_covering_windows(self):
        free = np.ones((3, 3), bool)
        feas = topology.topology_feasibility_host(free, 2, 2)
        # center node is covered by all four 2x2 windows
        covering = [
            feas.anchor_score[i, j] for i, j in [(0, 0), (0, 1), (1, 0), (1, 1)]
        ]
        assert int(feas.node_score[1, 1]) == int(min(covering))


class TestMeshView:
    def test_parses_coords_and_skips_unlabeled(self):
        nodes = make_mesh_nodes(2, 3) + [make_pod("not-a-mesh-node")]
        # a pod has no coord label; also add a malformed node
        from platform_aware_scheduling_tpu.testing.builders import make_node

        nodes.append(make_node("bad", labels={"pas-tpu-coord": "x,1"}))
        mesh = topology.MeshView([n for n in nodes if hasattr(n, "raw")])
        assert mesh.rows == 2 and mesh.cols == 3
        assert len(mesh) == 6
        assert mesh.coord_of["mesh-1-2"] == (1, 2)

    def test_free_mask_and_names_for(self):
        mesh = topology.MeshView(make_mesh_nodes(2, 2))
        mask = mesh.free_mask({"mesh-0-0", "mesh-1-1", "unknown"})
        assert mask.tolist() == [[True, False], [False, True]]
        assert mesh.names_for([(0, 0), (0, 1)]) == ["mesh-0-0", "mesh-0-1"]
        assert mesh.names_for([(5, 5)]) is None


# ---------------------------------------------------------------------------
# spec parsing
# ---------------------------------------------------------------------------


class TestGangSpec:
    def test_full_spec(self):
        spec = GangSpec.from_pod(make_gang_pod("p", "train", 8, "2x4"))
        assert spec.gang_id == "default/train"
        assert spec.size == 8
        assert spec.topology == (2, 4)
        assert spec.topology_label == "2x4"

    def test_size_only_spec(self):
        spec = GangSpec.from_pod(make_gang_pod("p", "train", 3))
        assert spec.size == 3 and spec.topology is None
        assert spec.topology_label == "any"

    def test_group_without_size_is_not_a_gang(self):
        pod = make_pod("p", labels={"pas-workload-group": "train"})
        assert GangSpec.from_pod(pod) is None

    @pytest.mark.parametrize(
        "size,topo",
        [("zero", ""), ("0", ""), ("8", "4x4"), ("8", "2by4"), ("8", "x")],
    )
    def test_malformed_specs_fail_open_to_non_gang(self, size, topo):
        labels = {"pas-workload-group": "g", "pas-gang-size": size}
        if topo:
            labels["pas-gang-topology"] = topo
        assert GangSpec.from_pod(make_pod("p", labels=labels)) is None


# ---------------------------------------------------------------------------
# tracker lifecycle
# ---------------------------------------------------------------------------


def make_tracker(rows=4, cols=4, ttl_s=30.0, use_device=True, clock=None):
    nodes = make_mesh_nodes(rows, cols)
    clock_box = clock or [0.0]
    tracker = GangTracker(
        nodes_provider=lambda: nodes,
        ttl_s=ttl_s,
        use_device=use_device,
        clock=lambda: clock_box[0],
    )
    names = [n.name for n in nodes]
    return tracker, names, clock_box


class TestGangTracker:
    def test_reservation_lifecycle(self):
        tracker, names, clock = make_tracker()
        before = trace.COUNTERS.get(
            "pas_gang_reservations_total", kind="counter"
        )
        failed, codes = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        assert tracker.gang_state("default/ga") == STATE_RESERVED
        allowed = sorted(set(names) - set(failed))
        assert len(allowed) == 4
        assert set(codes.values()) == {decisions.CODE_GANG_INFEASIBLE}
        assert (
            trace.COUNTERS.get("pas_gang_reservations_total", kind="counter")
            == before + 1
        )
        # bind all four members (each registered via its own filter)
        for i, node in enumerate(allowed):
            pod = make_gang_pod(f"a-{i}", "ga", 4, "2x2")
            tracker.filter_overlay(pod, names)
            tracker.observe_bind("default", f"a-{i}", node)
        assert tracker.gang_state("default/ga") == STATE_BOUND
        # release frees the slice
        assert tracker.release("default/ga")
        assert tracker.gang_state("default/ga") is None
        assert tracker.reserved_nodes() == {}

    def test_competing_gangs_serialize_on_disjoint_slices(self):
        tracker, names, _clock = make_tracker()
        failed_a, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 8, "2x4"), names
        )
        failed_b, codes_b = tracker.filter_overlay(
            make_gang_pod("b-0", "gb", 8, "2x4"), names
        )
        allowed_a = set(names) - set(failed_a)
        allowed_b = set(names) - set(failed_b)
        assert len(allowed_a) == 8 and len(allowed_b) == 8
        assert not (allowed_a & allowed_b)
        # gang B's view of gang A's slice carries the reserved code
        reserved_codes = {
            n: c
            for n, c in codes_b.items()
            if c == decisions.CODE_GANG_RESERVED
        }
        assert set(reserved_codes) == allowed_a

    def test_third_gang_rejected_when_mesh_is_full(self):
        tracker, names, _clock = make_tracker()
        tracker.filter_overlay(make_gang_pod("a-0", "ga", 8, "2x4"), names)
        tracker.filter_overlay(make_gang_pod("b-0", "gb", 8, "2x4"), names)
        before = trace.COUNTERS.get(
            "pas_gang_rejected_total",
            kind="counter",
            labels={"reason": "infeasible"},
        )
        failed_c, codes_c = tracker.filter_overlay(
            make_gang_pod("c-0", "gc", 8, "2x4"), names
        )
        assert set(failed_c) == set(names)  # all-or-nothing: nothing passes
        assert tracker.gang_state("default/gc") == STATE_FORMING
        assert all(
            c == decisions.CODE_GANG_INFEASIBLE for c in codes_c.values()
        )
        assert (
            trace.COUNTERS.get(
                "pas_gang_rejected_total",
                kind="counter",
                labels={"reason": "infeasible"},
            )
            == before + 1
        )

    def test_ttl_expiry_reclaims_the_slice(self):
        tracker, names, clock = make_tracker(ttl_s=10.0)
        tracker.filter_overlay(make_gang_pod("a-0", "ga", 8, "2x4"), names)
        before = trace.COUNTERS.get(
            "pas_gang_reservation_expirations_total", kind="counter"
        )
        clock[0] = 11.0
        assert tracker.prune() == 1
        assert tracker.gang_state("default/ga") == STATE_FORMING
        assert tracker.reserved_nodes() == {}
        assert (
            trace.COUNTERS.get(
                "pas_gang_reservation_expirations_total", kind="counter"
            )
            == before + 1
        )
        # a waiting gang can now take the freed slice
        failed_b, _ = tracker.filter_overlay(
            make_gang_pod("b-0", "gb", 16, "4x4"), names
        )
        assert len(set(names) - set(failed_b)) == 16

    def test_member_filter_refreshes_ttl(self):
        tracker, names, clock = make_tracker(ttl_s=10.0)
        tracker.filter_overlay(make_gang_pod("a-0", "ga", 8, "2x4"), names)
        clock[0] = 8.0  # touch before expiry
        tracker.filter_overlay(make_gang_pod("a-1", "ga", 8, "2x4"), names)
        clock[0] = 16.0  # past the original deadline, not the refreshed one
        assert tracker.prune() == 0
        assert tracker.gang_state("default/ga") == STATE_RESERVED

    def test_no_expiry_once_fully_bound(self):
        tracker, names, clock = make_tracker(ttl_s=10.0)
        allowed = None
        for i in range(4):
            failed, _ = tracker.filter_overlay(
                make_gang_pod(f"a-{i}", "ga", 4, "2x2"), names
            )
            allowed = sorted(set(names) - set(failed))
        for i, node in enumerate(allowed):
            tracker.observe_bind("default", f"a-{i}", node)
        assert tracker.gang_state("default/ga") == STATE_BOUND
        clock[0] = 1000.0
        assert tracker.prune() == 0
        assert tracker.gang_state("default/ga") == STATE_BOUND

    def test_size_only_gang_needs_no_mesh(self):
        tracker = GangTracker(nodes_provider=lambda: [], clock=lambda: 0.0)
        names = [f"n-{i}" for i in range(5)]
        failed, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 3), names
        )
        allowed = sorted(set(names) - set(failed))
        assert len(allowed) == 3  # deterministic: sorted-name order
        assert allowed == sorted(names)[:3]

    def test_topology_gang_without_mesh_rejected_no_mesh(self):
        tracker = GangTracker(nodes_provider=lambda: [], clock=lambda: 0.0)
        names = [f"n-{i}" for i in range(16)]
        failed, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        assert set(failed) == set(names)
        assert "no mesh coordinates" in failed[names[0]]

    def test_non_gang_pod_fails_only_reserved_nodes(self):
        tracker, names, _clock = make_tracker()
        failed_a, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        slice_a = set(names) - set(failed_a)
        failed, codes = tracker.filter_overlay(make_pod("plain"), names)
        assert set(failed) == slice_a
        assert all(
            c == decisions.CODE_GANG_RESERVED for c in codes.values()
        )
        assert "reserved by gang default/ga" in failed[sorted(slice_a)[0]]

    def test_admitted_counter_and_histogram(self):
        tracker, names, clock = make_tracker()
        before = trace.COUNTERS.get("pas_gang_admitted_total", kind="counter")
        from platform_aware_scheduling_tpu.gang.group import FULL_GANG_LATENCY

        hist_before = FULL_GANG_LATENCY.summary("2x2")["count"]
        failed, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        allowed = sorted(set(names) - set(failed))
        clock[0] = 2.5
        for i, node in enumerate(allowed):
            tracker.filter_overlay(
                make_gang_pod(f"a-{i}", "ga", 4, "2x2"), names
            )
            tracker.observe_bind("default", f"a-{i}", node)
        assert (
            trace.COUNTERS.get("pas_gang_admitted_total", kind="counter")
            == before + 1
        )
        summary = FULL_GANG_LATENCY.summary("2x2")
        assert summary["count"] == hist_before + 1
        assert summary["max"] >= 2.5

    def test_expiry_discards_stale_binds(self):
        """Review fix: binds on an abandoned slice must not count toward
        admission after a re-reservation — a gang can never be admitted
        straddling two slices."""
        tracker, names, clock = make_tracker(ttl_s=10.0)
        failed, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        old_slice = sorted(set(names) - set(failed))
        for i in range(2):  # partial: 2 of 4 bind, then the TTL lapses
            tracker.filter_overlay(
                make_gang_pod(f"a-{i}", "ga", 4, "2x2"), names
            )
            tracker.observe_bind("default", f"a-{i}", old_slice[i])
        clock[0] = 11.0
        assert tracker.prune() == 1
        # steal part of the old slice so the re-reservation moves
        tracker.filter_overlay(
            make_gang_pod("x-0", "gx", 4, "1x4"), [old_slice[0]] + names
        )
        failed2, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        new_slice = sorted(set(names) - set(failed2))
        # two fresh binds are NOT enough — the old ones were discarded
        for i, node in enumerate(new_slice[:2]):
            tracker.filter_overlay(
                make_gang_pod(f"a-{i}", "ga", 4, "2x2"), names
            )
            tracker.observe_bind("default", f"a-{i}", node)
        assert tracker.gang_state("default/ga") == STATE_RESERVED
        for i, node in enumerate(new_slice[2:], start=2):
            tracker.filter_overlay(
                make_gang_pod(f"a-{i}", "ga", 4, "2x2"), names
            )
            tracker.observe_bind("default", f"a-{i}", node)
        assert tracker.gang_state("default/ga") == STATE_BOUND

    def test_dead_gang_sweep_releases_completed_jobs(self):
        """Review fix: a bound gang whose pods have all disappeared is
        released by the periodic sweep, so a finished job's slice cannot
        stay reserved until restart."""
        nodes = make_mesh_nodes(4, 4)
        clock = [0.0]
        live_pods = []
        tracker = GangTracker(
            nodes_provider=lambda: nodes,
            pods_provider=lambda: list(live_pods),
            ttl_s=30.0,
            mesh_max_age_s=5.0,
            clock=lambda: clock[0],
        )
        names = [n.name for n in nodes]
        failed, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        allowed = sorted(set(names) - set(failed))
        for i, node in enumerate(allowed):
            pod = make_gang_pod(f"a-{i}", "ga", 4, "2x2")
            live_pods.append(pod)
            tracker.filter_overlay(pod, names)
            tracker.observe_bind("default", f"a-{i}", node)
        assert tracker.gang_state("default/ga") == STATE_BOUND
        clock[0] = 10.0
        assert tracker.prune() == 0  # members alive: the hold persists
        assert tracker.gang_state("default/ga") == STATE_BOUND
        live_pods.clear()  # the job finishes; its pods are deleted
        clock[0] = 20.0
        tracker.prune()
        assert tracker.gang_state("default/ga") is None
        assert tracker.reserved_nodes() == {}

    def test_sweep_treats_succeeded_pods_as_dead(self):
        """Review fix: a completed Job's pods linger as Succeeded until
        GC — they no longer run on the slice, so the sweep must release
        the hold (same liveness rule as the actuator's group floor)."""
        nodes = make_mesh_nodes(4, 4)
        clock = [0.0]
        pods = []
        tracker = GangTracker(
            nodes_provider=lambda: nodes,
            pods_provider=lambda: list(pods),
            mesh_max_age_s=5.0,
            clock=lambda: clock[0],
        )
        names = [n.name for n in nodes]
        failed, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        for i, node in enumerate(sorted(set(names) - set(failed))):
            pod = make_gang_pod(
                f"a-{i}", "ga", 4, "2x2", phase="Succeeded"
            )
            pods.append(pod)
            tracker.filter_overlay(pod, names)
            tracker.observe_bind("default", f"a-{i}", node)
        assert tracker.gang_state("default/ga") == STATE_BOUND
        clock[0] = 10.0
        tracker.prune()
        assert tracker.gang_state("default/ga") is None

    def test_sweep_never_blocks_the_filter_path(self):
        """Review fix: the sweep's cluster pod LIST runs off the verb's
        thread — a hung pods_provider must not stall filter_overlay."""
        import threading as _threading
        import time as _time

        release_provider = _threading.Event()

        def slow_pods():
            release_provider.wait(10.0)
            return []

        nodes = make_mesh_nodes(2, 2)
        clock = [0.0]
        tracker = GangTracker(
            nodes_provider=lambda: nodes,
            pods_provider=slow_pods,
            mesh_max_age_s=0.0,  # every call is sweep-eligible
            clock=lambda: clock[0],
        )
        names = [n.name for n in nodes]
        # put a bound gang in place so the sweep has work to hand off
        failed, _ = tracker.filter_overlay(
            make_gang_pod("a-0", "ga", 4, "2x2"), names
        )
        for i, node in enumerate(sorted(set(names) - set(failed))):
            tracker.filter_overlay(
                make_gang_pod(f"a-{i}", "ga", 4, "2x2"), names
            )
            tracker.observe_bind("default", f"a-{i}", node)
        clock[0] = 1.0
        t0 = _time.perf_counter()
        tracker.filter_overlay(make_pod("plain"), names)
        elapsed = _time.perf_counter() - t0
        release_provider.set()
        assert elapsed < 2.0, f"filter blocked {elapsed:.1f}s on the sweep"

    def test_mesh_coordinates_are_sanity_bounded(self):
        """Review fix: one mislabeled coordinate must not size the dense
        mesh grids into the terabytes — out-of-bound coords parse as
        no-coordinate (the node sits outside the mesh)."""
        from platform_aware_scheduling_tpu.testing.builders import make_node
        from platform_aware_scheduling_tpu.utils import labels as shared

        assert shared.parse_coord({"pas-tpu-coord": "1000000,1000000"}) is None
        assert shared.parse_coord(
            {"pas-tpu-coord": f"{shared.MAX_MESH_DIM},0"}
        ) is None
        assert shared.parse_coord(
            {"pas-tpu-coord": f"{shared.MAX_MESH_DIM - 1},0"}
        ) == (shared.MAX_MESH_DIM - 1, 0)
        nodes = make_mesh_nodes(2, 2) + [
            make_node("rogue", labels={"pas-tpu-coord": "999999,999999"})
        ]
        mesh = topology.MeshView(nodes)
        assert (mesh.rows, mesh.cols) == (2, 2)  # the rogue node is ignored

    def test_prioritize_first_reservation_avoids_violating_nodes(self):
        """Review fix: a Prioritize-FIRST gang arrival solves over the
        same telemetry-clean candidates Filter would — it cannot reserve
        a slice containing a violating node."""
        from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
        from platform_aware_scheduling_tpu.utils.quantity import Quantity

        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        hot = {n for n in names if n.startswith(("mesh-0-", "mesh-1-"))}
        extender.cache.write_metric(
            "mesh_metric",
            {
                n: NodeMetric(value=Quantity(2 * 10**9 if n in hot else 1))
                for n in names
            },
        )
        response = _post(
            extender,
            "prioritize",
            {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
             "NodeNames": names},
        )
        ranked = [e["Host"] for e in json.loads(response.body)]
        assert ranked and not (set(ranked) & hot)

    def test_prioritize_overlay_ranks_reserved_slice(self):
        tracker, names, _clock = make_tracker()
        pod = make_gang_pod("a-0", "ga", 4, "2x2")
        failed, _ = tracker.filter_overlay(pod, names)
        reserved = [n for n in names if n not in failed]
        ranked = tracker.prioritize_overlay(pod, names)
        assert [hp.host for hp in ranked] == reserved  # row-major slice order
        assert [hp.score for hp in ranked] == [10, 9, 8, 7]
        assert tracker.prioritize_overlay(make_pod("plain"), names) is None

    def test_device_and_host_trackers_choose_identical_slices(self):
        results = []
        for use_device in (True, False):
            tracker, names, _clock = make_tracker(use_device=use_device)
            # carve an irregular free region via a blocking gang
            tracker.filter_overlay(
                make_gang_pod("x-0", "gx", 4, "1x4"), names
            )
            failed, _ = tracker.filter_overlay(
                make_gang_pod("a-0", "ga", 6, "2x3"), names
            )
            results.append(sorted(set(names) - set(failed)))
        assert results[0] == results[1]


# ---------------------------------------------------------------------------
# verb integration (in-process)
# ---------------------------------------------------------------------------


class TestVerbIntegration:
    def test_gang_member_filter_passes_only_slice_with_concrete_reasons(self):
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        pod = _gang_pod_obj("a-0", "gang-a", 8, "2x4")
        response = _post(
            extender, "filter", {"Pod": pod, "NodeNames": names}
        )
        assert response.status == 200
        obj = json.loads(response.body)
        assert len(obj["NodeNames"]) == 8
        assert len(obj["FailedNodes"]) == 8
        assert all(
            "outside reserved 2x4 slice" in reason
            for reason in obj["FailedNodes"].values()
        )

    def test_competing_gang_sees_reserved_reason(self):
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        _post(
            extender,
            "filter",
            {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
             "NodeNames": names},
        )
        response = _post(
            extender,
            "filter",
            {"Pod": _gang_pod_obj("b-0", "gang-b", 8, "2x4"),
             "NodeNames": names},
        )
        failed = json.loads(response.body)["FailedNodes"]
        assert any(
            "reserved by gang default/gang-a" in reason
            for reason in failed.values()
        )

    def test_decision_records_carry_gang_reason_codes(self):
        decisions.DECISIONS.configure(enabled=True, capacity=64)
        try:
            extender, _kube, names = build_mesh_service(4, 4, gang=True)
            before_res = trace.COUNTERS.get(
                "pas_decision_filtered_nodes_total",
                kind="counter",
                labels={"reason": "gang_reserved"},
            )
            before_inf = trace.COUNTERS.get(
                "pas_decision_filtered_nodes_total",
                kind="counter",
                labels={"reason": "gang_infeasible"},
            )
            _post(
                extender,
                "filter",
                {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
                 "NodeNames": names},
            )
            _post(
                extender,
                "filter",
                {"Pod": _gang_pod_obj("b-0", "gang-b", 8, "2x4"),
                 "NodeNames": names},
            )
            # gang B's record: 8 nodes held by A (gang_reserved)
            assert (
                trace.COUNTERS.get(
                    "pas_decision_filtered_nodes_total",
                    kind="counter",
                    labels={"reason": "gang_reserved"},
                )
                == before_res + 8
            )
            # gang A's record: 8 nodes outside its slice (gang_infeasible)
            assert (
                trace.COUNTERS.get(
                    "pas_decision_filtered_nodes_total",
                    kind="counter",
                    labels={"reason": "gang_infeasible"},
                )
                >= before_inf + 8
            )
            snap = decisions.DECISIONS.snapshot(verb="filter", limit=4)
            assert snap["returned"] >= 2
            record = snap["records"][0]
            assert any(
                "gang" in reason for reason in record["violating"].values()
            )
        finally:
            decisions.DECISIONS.configure(enabled=True, capacity=512)

    def test_prioritize_serves_gang_slice_in_anchor_order(self):
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        pod = _gang_pod_obj("a-0", "gang-a", 8, "2x4")
        passing = _filter_passing(extender, pod, names)
        response = _post(
            extender, "prioritize", {"Pod": pod, "NodeNames": names}
        )
        ranked = json.loads(response.body)
        assert [e["Host"] for e in ranked] == passing
        assert ranked[0]["Score"] == 10

    def test_bind_promotes_and_releases_nothing_until_full(self):
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        pods = [_gang_pod_obj(f"a-{i}", "gang-a", 8, "2x4") for i in range(8)]
        passing = _filter_passing(extender, pods[0], names)
        for pod in pods[1:]:
            _filter_passing(extender, pod, names)
        for pod, node in zip(pods[:7], passing):
            _bind(extender, pod, node)
        assert extender.gangs.gang_state("default/gang-a") == STATE_RESERVED
        _bind(extender, pods[7], passing[7])
        assert extender.gangs.gang_state("default/gang-a") == STATE_BOUND

    def test_reservation_avoids_telemetry_violating_nodes(self):
        """Review fix: the reservation solve's free mask excludes nodes
        the telemetry Filter already marked violating — the gang lands
        on a clean slice instead of livelocking on one it can never
        fully bind."""
        from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
        from platform_aware_scheduling_tpu.utils.quantity import Quantity

        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        # rows 0-1 violate the dontschedule rule (value > 10^9)
        hot = {n for n in names if n.startswith(("mesh-0-", "mesh-1-"))}
        extender.cache.write_metric(
            "mesh_metric",
            {
                n: NodeMetric(
                    value=Quantity(2 * 10**9 if n in hot else 1)
                )
                for n in names
            },
        )
        response = _post(
            extender,
            "filter",
            {"Pod": _gang_pod_obj("a-0", "gang-a", 8, "2x4"),
             "NodeNames": names},
        )
        obj = json.loads(response.body)
        passing = set(obj["NodeNames"])
        assert passing == {
            n for n in names if n.startswith(("mesh-2-", "mesh-3-"))
        }
        # the hot rows kept their telemetry reason, not a gang reason
        assert all(
            "threshold" in obj["FailedNodes"][n] for n in sorted(hot)
        )

    def test_non_gang_filtering_unchanged_without_tracker(self):
        """gang=off keeps the stock path: same candidates pass, and the
        response cache is probed as before (bypass counter untouched by
        plain pods)."""
        extender, _kube, names = build_mesh_service(4, 4, gang=False)
        pod = {
            "metadata": {
                "name": "plain",
                "namespace": "default",
                "labels": {"telemetry-policy": "gang-pol"},
            }
        }
        response = _post(
            extender, "filter", {"Pod": pod, "NodeNames": names}
        )
        obj = json.loads(response.body)
        assert sorted(obj["NodeNames"]) == sorted(names)
        assert obj["FailedNodes"] == {}


# ---------------------------------------------------------------------------
# the acceptance invariant, over real sockets on both front-ends
# ---------------------------------------------------------------------------


def _socket_schedule_two_gangs(server, names):
    """Drive the full admit loop over real sockets: strict A/B pod
    interleave, Filter -> Prioritize -> Bind per pod, until quiescent.
    Returns {group: [bound nodes]} and the unplaced pod count."""
    port = server.port
    pods = []
    for i in range(8):
        pods.append(_gang_pod_obj(f"a-{i}", "gang-a", 8, "2x4"))
        pods.append(_gang_pod_obj(f"b-{i}", "gang-b", 8, "2x4"))
    available = list(names)
    bound = {"gang-a": [], "gang-b": []}
    pending = list(pods)
    for _round in range(12):
        progressed = []
        for pod in pending:
            body = json.dumps({"Pod": pod, "NodeNames": available}).encode()
            status, _h, payload = raw_request(
                port, post_bytes("/scheduler/filter", body)
            )
            assert status == 200
            passing = json.loads(payload).get("NodeNames") or []
            if not passing:
                continue
            body = json.dumps({"Pod": pod, "NodeNames": passing}).encode()
            status, _h, payload = raw_request(
                port, post_bytes("/scheduler/prioritize", body)
            )
            ranked = json.loads(payload or b"[]") or []
            node = (
                max(ranked, key=lambda e: e["Score"])["Host"]
                if ranked
                else passing[0]
            )
            bind_body = json.dumps(
                {
                    "PodName": pod["metadata"]["name"],
                    "PodNamespace": "default",
                    "PodUID": "uid",
                    "Node": node,
                }
            ).encode()
            status, _h, _payload = raw_request(
                port, post_bytes("/scheduler/bind", bind_body)
            )
            assert status == 404  # TAS bind parity: 404, feedback consumed
            available.remove(node)
            group = pod["metadata"]["labels"]["pas-workload-group"]
            bound[group].append(node)
            progressed.append(pod)
        if not progressed:
            break
        pending = [p for p in pending if p not in progressed]
    return bound, len(pending)


@pytest.mark.parametrize("serving", ["threaded", "async"])
class TestAllOrNothingOverSockets:
    def test_two_competing_gangs_both_fully_bind(self, serving):
        extender, kube, names = build_mesh_service(4, 4, gang=True)
        server = (
            start_async(extender) if serving == "async"
            else start_threaded(extender)
        )
        try:
            bound, unplaced = _socket_schedule_two_gangs(server, names)
            assert unplaced == 0
            assert len(bound["gang-a"]) == 8 and len(bound["gang-b"]) == 8
            assert not (set(bound["gang-a"]) & set(bound["gang-b"]))
            mesh = topology.MeshView(kube.list_nodes())
            for group in ("gang-a", "gang-b"):
                mask = mesh.free_mask(bound[group])
                feas = topology.topology_feasibility_host(mask, 2, 4)
                assert feas.anchor_ok.any(), f"{group} is not a valid slice"
            assert extender.gangs.gang_state("default/gang-a") == STATE_BOUND
            assert extender.gangs.gang_state("default/gang-b") == STATE_BOUND
        finally:
            server.shutdown()

    def test_gang_off_deadlocks_half_placed(self, serving):
        """The control: same interleave over the same sockets with no
        tracker — every pod binds, but NEITHER gang's node set forms a
        contiguous 2x4 slice (the half-placed deadlock)."""
        extender, kube, names = build_mesh_service(4, 4, gang=False)
        server = (
            start_async(extender) if serving == "async"
            else start_threaded(extender)
        )
        try:
            bound, unplaced = _socket_schedule_two_gangs(server, names)
            assert unplaced == 0  # everything "scheduled"...
            mesh = topology.MeshView(kube.list_nodes())
            valid = 0
            for group in ("gang-a", "gang-b"):
                mask = mesh.free_mask(bound[group])
                for h, w in ((2, 4), (4, 2)):
                    feas = topology.topology_feasibility_host(mask, h, w)
                    if feas.anchor_ok.any():
                        valid += 1
                        break
            assert valid == 0  # ...but no gang ever forms a valid slice
        finally:
            server.shutdown()

    def test_no_incomplete_gang_member_binds_after_ttl_expiry(self, serving):
        clock = [0.0]
        extender, _kube, names = build_mesh_service(
            4, 4, gang=True, ttl_s=10.0
        )
        extender.gangs._clock = lambda: clock[0]
        server = (
            start_async(extender) if serving == "async"
            else start_threaded(extender)
        )
        try:
            port = server.port
            pod = _gang_pod_obj("a-0", "gang-a", 8, "2x4")
            body = json.dumps({"Pod": pod, "NodeNames": names}).encode()
            status, _h, payload = raw_request(
                port, post_bytes("/scheduler/filter", body)
            )
            assert len(json.loads(payload)["NodeNames"]) == 8
            clock[0] = 11.0  # reservation lapses with zero binds
            status, _h, payload = raw_request(
                port, post_bytes("/scheduler/filter", body)
            )
            # the expired gang re-forms and re-reserves atomically in the
            # same verdict — never a stale half-hold
            obj = json.loads(payload)
            assert len(obj["NodeNames"]) == 8
            assert extender.gangs.gang_state("default/gang-a") == (
                STATE_RESERVED
            )
            # an expired reservation's nodes went back to the pool first:
            # the expiration was counted
            assert (
                trace.COUNTERS.get(
                    "pas_gang_reservation_expirations_total", kind="counter"
                )
                >= 1
            )
        finally:
            server.shutdown()


class TestDeadlockAB:
    def test_gang_on_admits_both_gang_off_deadlocks(self):
        """The bench scenario IS the acceptance test: same verbs, same
        interleave.  gang-on -> both gangs form valid 2x4 slices;
        gang-off -> every pod binds but NEITHER gang is a valid slice."""
        result = run_deadlock_ab()
        assert result["gang_on"]["gangs_admitted_as_valid_slice"] == 2
        assert result["gang_on"]["deadlock"] is False
        assert result["gang_off"]["deadlock"] is True

    def test_device_host_wire_parity_byte_exact(self):
        """The same gang scenario through a device-kernel tracker and a
        host-mirror tracker produces byte-identical wire responses."""
        bodies = {}
        for use_device in (True, False):
            extender, _kube, names = build_mesh_service(4, 4, gang=True)
            extender.gangs.use_device = use_device
            responses = []
            for group in ("gang-a", "gang-b", "gang-c"):
                pod = _gang_pod_obj(f"{group}-0", group, 8, "2x4")
                for verb in ("filter", "prioritize"):
                    response = _post(
                        extender, verb, {"Pod": pod, "NodeNames": names}
                    )
                    responses.append((verb, response.status, response.body))
            bodies[use_device] = responses
        assert bodies[True] == bodies[False]


# ---------------------------------------------------------------------------
# /debug/gangs
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("serving", ["threaded", "async"])
class TestDebugGangsEndpoint:
    def test_states_served_and_404_when_unwired(self, serving):
        extender, _kube, names = build_mesh_service(4, 4, gang=True)
        _filter_passing(
            extender, _gang_pod_obj("a-0", "gang-a", 8, "2x4"), names
        )
        server = (
            start_async(extender) if serving == "async"
            else start_threaded(extender)
        )
        try:
            status, _h, payload = get_request(server.port, "/debug/gangs")
            assert status == 200
            snap = json.loads(payload)
            assert snap["enabled"] is True
            assert snap["mesh"] == {"rows": 4, "cols": 4, "nodes": 16}
            assert snap["gangs"][0]["gang"] == "default/gang-a"
            assert snap["gangs"][0]["state"] == "reserved"
            assert snap["gangs"][0]["anchor"]["rows"] == 2
            assert snap["reserved_nodes"] == 8
            # non-GET is 405
            status, _h, _payload = raw_request(
                server.port,
                post_bytes("/debug/gangs", b"{}"),
            )
            assert status == 405
        finally:
            server.shutdown()
        extender_off, _kube2, _names2 = build_mesh_service(4, 4, gang=False)
        server = (
            start_async(extender_off) if serving == "async"
            else start_threaded(extender_off)
        )
        try:
            status, _h, _payload = get_request(server.port, "/debug/gangs")
            assert status == 404
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# gang-atomic eviction
# ---------------------------------------------------------------------------


def _gang_cluster(kube, n=4):
    """n bound gang pods + one plain pod on a fake cluster; returns
    (pods by key, all pods, moves for the gang)."""
    from platform_aware_scheduling_tpu.rebalance.replan import Move

    pods = []
    for i in range(n):
        pod = make_gang_pod(
            f"g-{i}", "train", n, node_name=f"node-{i}", phase="Running"
        )
        kube.add_pod(pod)
        pods.append(pod)
    plain = make_pod("plain", node_name="node-9", phase="Running")
    kube.add_pod(plain)
    pods_by_key = {f"default/{p.name}": p for p in pods + [plain]}
    moves = [
        Move(
            pod_key=f"default/g-{i}",
            namespace="default",
            name=f"g-{i}",
            from_node=f"node-{i}",
            to_node="node-x",
            gain=1.0,
        )
        for i in range(n)
    ]
    return pods_by_key, pods + [plain], moves


class TestGangAtomicEviction:
    def test_partial_gang_moves_all_skip(self):
        kube = FakeKubeClient()
        pods_by_key, all_pods, moves = _gang_cluster(kube)
        actuator = SafeActuator(kube, mode="active", min_available=0, burst=8)
        result = actuator.actuate(moves[:2], pods_by_key, all_pods)
        assert result.executed == []
        assert result.skip_counts() == {"gang_partial": 2}
        assert kube.evictions == []

    def test_whole_gang_evicts_atomically(self):
        kube = FakeKubeClient()
        pods_by_key, all_pods, moves = _gang_cluster(kube)
        actuator = SafeActuator(kube, mode="active", min_available=0, burst=8)
        result = actuator.actuate(moves, pods_by_key, all_pods)
        assert len(result.executed) == 4
        assert len(kube.evictions) == 4

    def test_rate_gate_is_all_or_nothing_for_a_gang(self):
        kube = FakeKubeClient()
        pods_by_key, all_pods, moves = _gang_cluster(kube)
        # burst 2 < gang size 4: the whole gang waits, nothing partial
        actuator = SafeActuator(kube, mode="active", min_available=0, burst=2)
        result = actuator.actuate(moves, pods_by_key, all_pods)
        assert result.executed == []
        assert result.skip_counts() == {"rate_limit": 4}
        assert kube.evictions == []

    def test_min_available_floor_gates_the_whole_gang(self):
        kube = FakeKubeClient()
        pods_by_key, all_pods, moves = _gang_cluster(kube)
        actuator = SafeActuator(kube, mode="active", min_available=1, burst=8)
        result = actuator.actuate(moves, pods_by_key, all_pods)
        assert result.executed == []
        assert result.skip_counts() == {"min_available": 4}

    def test_dry_run_records_whole_gang_as_dry_run(self):
        kube = FakeKubeClient()
        pods_by_key, all_pods, moves = _gang_cluster(kube)
        actuator = SafeActuator(
            kube, mode="dry-run", min_available=0, burst=8
        )
        result = actuator.actuate(moves, pods_by_key, all_pods)
        assert result.skip_counts() == {"dry_run": 4}
        assert kube.evictions == []

    def test_whole_gang_evicts_with_production_pod_keys(self):
        """Review fix: membership completeness is compared via
        object_key on the Pod objects, so the production pod_key format
        (``ns&name`` from replan's object_key) matches too — a whole-gang
        plan must evict, not skip gang_partial."""
        from platform_aware_scheduling_tpu.kube.objects import object_key
        from platform_aware_scheduling_tpu.rebalance.replan import Move

        kube = FakeKubeClient()
        pods = []
        for i in range(4):
            pod = make_gang_pod(
                f"g-{i}", "train", 4, node_name=f"node-{i}", phase="Running"
            )
            kube.add_pod(pod)
            pods.append(pod)
        pods_by_key = {object_key(p): p for p in pods}  # "default&g-0"
        moves = [
            Move(
                pod_key=object_key(p),
                namespace="default",
                name=p.name,
                from_node=f"node-{i}",
                to_node="node-x",
                gain=1.0,
            )
            for i, p in enumerate(pods)
        ]
        actuator = SafeActuator(kube, mode="active", min_available=0, burst=8)
        result = actuator.actuate(moves, pods_by_key, pods)
        assert len(result.executed) == 4
        assert result.skip_counts() == {}

    def test_whole_gang_eviction_releases_the_reservation(self):
        """Review fix: a fully-evicted gang's slice goes back to the
        pool (actuator -> tracker release hook, wired by assemble)."""
        from platform_aware_scheduling_tpu.rebalance.replan import Move

        tracker, names, _clock = make_tracker()
        kube = FakeKubeClient()
        pods = []
        failed, _ = tracker.filter_overlay(
            make_gang_pod("g-0", "train", 4, "2x2"), names
        )
        allowed = sorted(set(names) - set(failed))
        for i, node in enumerate(allowed):
            pod = make_gang_pod(
                "g-%d" % i, "train", 4, "2x2",
                node_name=node, phase="Running",
            )
            kube.add_pod(pod)
            pods.append(pod)
            tracker.filter_overlay(pod, names)
            tracker.observe_bind("default", f"g-{i}", node)
        assert tracker.gang_state("default/train") == STATE_BOUND
        pods_by_key = {f"default/{p.name}": p for p in pods}
        moves = [
            Move(
                pod_key=f"default/{p.name}",
                namespace="default",
                name=p.name,
                from_node="n",
                to_node="m",
                gain=1.0,
            )
            for p in pods
        ]
        actuator = SafeActuator(kube, mode="active", min_available=0, burst=8)
        actuator.gang_tracker = tracker
        result = actuator.actuate(moves, pods_by_key, pods)
        assert len(result.executed) == 4
        assert tracker.gang_state("default/train") is None
        assert tracker.reserved_nodes() == {}

    def test_malformed_gang_labels_are_non_gang_everywhere(self):
        """Review fix: one classifier (labels.gang_id_for) for scheduler
        AND actuator — a malformed size label means plain-pod semantics
        in both, so the pod stays evictable."""
        from platform_aware_scheduling_tpu.rebalance.replan import Move
        from platform_aware_scheduling_tpu.utils import labels as shared

        bad_labels = {
            "pas-workload-group": "train",
            "pas-gang-size": "not-a-number",
        }
        assert shared.gang_id_for("default", bad_labels) is None
        assert GangSpec.from_pod(make_pod("p", labels=bad_labels)) is None
        # topology inconsistent with size is equally non-gang
        assert (
            shared.gang_id_for(
                "default",
                {**bad_labels, "pas-gang-size": "8",
                 "pas-gang-topology": "3x3"},
            )
            is None
        )
        kube = FakeKubeClient()
        pod = make_pod(
            "p", labels=bad_labels, node_name="node-0", phase="Running"
        )
        kube.add_pod(pod)
        move = Move(
            pod_key="default/p", namespace="default", name="p",
            from_node="node-0", to_node="node-x", gain=1.0,
        )
        actuator = SafeActuator(kube, mode="active", min_available=0, burst=8)
        result = actuator.actuate([move], {"default/p": pod}, [pod])
        assert len(result.executed) == 1  # evicted as a plain pod

    def test_plain_pods_keep_the_stock_gates(self):
        from platform_aware_scheduling_tpu.rebalance.replan import Move

        kube = FakeKubeClient()
        pods_by_key, all_pods, _moves = _gang_cluster(kube)
        move = Move(
            pod_key="default/plain",
            namespace="default",
            name="plain",
            from_node="node-9",
            to_node="node-x",
            gain=1.0,
        )
        actuator = SafeActuator(kube, mode="active", min_available=0, burst=8)
        result = actuator.actuate([move], pods_by_key, all_pods)
        assert len(result.executed) == 1


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))

"""Priority-aware admission plane suite (ISSUE 16, docs/admission.md).

Covers the whole subsystem:

  * priority classes: one ``pas-priority`` label validator, default
    fallback for unlabeled/unknown pods, malformed ladders fail fast;
  * the bounded queue: (class, arrival) head-of-line order, overflow
    shedding (worst-ranked entry, or the arrival itself when it ranks
    worst), the fairness-streak override, backfill (spare-nodes and
    covered-by-reservation), starvation accounting past the consult
    threshold, terminal drops, bind feedback;
  * victim selection + atomic execution: never equal-or-higher class,
    whole gangs only, leader-gated, bounded appetite, retry throttle,
    and fenced-refusal containment (an aborted plan creates NO
    reservation);
  * flag wiring: --preemption=on demands --admission=on AND --gang=on
    (exit 2 with usage), GAS offers no --preemption at all, malformed
    class ladders exit 2, --admission=off builds nothing;
  * the off-path pin: without a plane the verbs serve byte-identically,
    /debug/admission is 404, and zero pas_admission_* families register;
  * torus wraparound feasibility device<->host parity (ops/topology);
  * the ACCEPTANCE scenarios over real sockets on BOTH front-ends:
    priority inversion held at the gate, backfill without starvation,
    and the preemption cascade ON vs OFF head-to-head.
"""

import json

import numpy as np
import pytest

from benchmarks.gang_load import _post, build_mesh_service
from platform_aware_scheduling_tpu.admission import (
    AdmissionPlane,
    PreemptionPlanner,
    blocked_reason,
)
from platform_aware_scheduling_tpu.gang import GangTracker
from platform_aware_scheduling_tpu.ops import topology
from platform_aware_scheduling_tpu.rebalance.actuator import (
    MODE_ACTIVE,
    MODE_DRY_RUN,
    SafeActuator,
)
from platform_aware_scheduling_tpu.testing import twin as tw
from platform_aware_scheduling_tpu.testing.builders import (
    make_gang_pod,
    make_pod,
)
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.utils import decisions
from platform_aware_scheduling_tpu.utils import labels as shared_labels
from wirehelpers import get_request, start_async, start_threaded

HIGH = {shared_labels.PRIORITY_LABEL: "high"}
BATCH = {shared_labels.PRIORITY_LABEL: "batch"}


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def now(self) -> float:
        return self.t

    def advance(self, s: float) -> None:
        self.t += s


class _Leader:
    def __init__(self, ok: bool):
        self.ok = ok

    def is_leader(self) -> bool:
        return self.ok


def _plane(**kw):
    clock = _Clock()
    kw.setdefault("clock", clock.now)
    kw.setdefault(
        "decision_log", decisions.DecisionLog(clock=clock.now)
    )
    return AdmissionPlane(**kw), clock


def _consult(plane, pod, nodes):
    """Filter passed on every candidate: the gate decides."""
    return plane.review(pod, list(nodes), {}, {})


def _miss(plane, pod, nodes, code=decisions.CODE_GANG_INFEASIBLE):
    """Filter failed on every candidate with one uniform code."""
    failed = {n: "x" for n in nodes}
    codes = {n: code for n in nodes}
    return plane.review(pod, list(nodes), failed, codes)


def _counter(plane, name, **labels):
    return plane.counters.get(
        name, kind="counter", labels=labels or None
    )


def _events(plane, verb="admission"):
    return plane.decision_log.snapshot(verb=verb, limit=64)["records"]


# ---------------------------------------------------------------------------
# priority classes
# ---------------------------------------------------------------------------


class TestPriorityClasses:
    def test_label_classifies(self):
        plane, _ = _plane()
        assert plane.classify(make_pod("p", labels=HIGH)) == ("high", 0)
        assert plane.classify(make_pod("p", labels=BATCH)) == ("batch", 2)

    def test_unlabeled_takes_default(self):
        plane, _ = _plane()
        assert plane.classify(make_pod("p")) == ("normal", 1)

    def test_unknown_class_takes_default(self):
        plane, _ = _plane()
        pod = make_pod(
            "p", labels={shared_labels.PRIORITY_LABEL: "platinum"}
        )
        assert plane.classify(pod) == ("normal", 1)

    def test_malformed_ladders_rejected(self):
        with pytest.raises(ValueError):
            AdmissionPlane(classes=("a", "a"))
        with pytest.raises(ValueError):
            AdmissionPlane(classes=())
        with pytest.raises(ValueError):
            AdmissionPlane(classes=("a", "b"), default_class="c")

    def test_gang_class_remembered_for_the_census(self):
        plane, _ = _plane()
        pod = make_gang_pod("g-0", "gang-b", 4, labels=dict(BATCH))
        _consult(plane, pod, ["n1"])
        assert plane.class_of_gang("default/gang-b") == "batch"
        assert plane.rank_of_gang("default/gang-b") == 2
        # a gang the plane never saw defaults, like an unlabeled pod
        assert plane.class_of_gang("never-seen") == "normal"


# ---------------------------------------------------------------------------
# the bounded queue
# ---------------------------------------------------------------------------


class TestBoundedQueue:
    def test_capacity_miss_enqueues_in_class_order(self):
        plane, _ = _plane()
        assert _miss(plane, make_pod("b1", labels=BATCH), ["n1"]) is None
        assert _miss(plane, make_pod("h1", labels=HIGH), ["n1"]) is None
        snap = plane.snapshot()
        assert snap["depth"] == 2
        # (class, arrival): the later-arriving high pod heads the queue
        assert [e["pod"] for e in snap["queue"]] == [
            "default/h1",
            "default/b1",
        ]
        assert _counter(
            plane, "pas_admission_queued_total", **{"class": "high"}
        ) == 1.0

    def test_lower_class_held_behind_queued_higher_class(self):
        plane, _ = _plane()
        _miss(plane, make_gang_pod("h0", "g-h", 8, labels=dict(HIGH)),
              ["n1", "n2"])
        verdict = _consult(plane, make_pod("b1", labels=BATCH), ["n1"])
        assert verdict is not None
        failed, codes = verdict
        assert failed == {"n1": blocked_reason("high", 2)}
        assert codes == {"n1": decisions.CODE_ADMISSION_BLOCKED}
        # the hold pinned its arrival order: it now waits in the queue
        snap = plane.snapshot()
        assert snap["depth"] == 2
        assert _counter(
            plane, "pas_admission_blocked_total", **{"class": "batch"}
        ) == 1.0

    def test_higher_class_never_blocked_by_lower(self):
        plane, _ = _plane()
        _miss(plane, make_pod("b1", labels=BATCH), ["n1"])
        assert _consult(plane, make_pod("h1", labels=HIGH), ["n1"]) is None
        assert _counter(
            plane, "pas_admission_admitted_total", **{"class": "high"}
        ) == 1.0

    def test_overflow_sheds_worst_ranked_entry(self):
        plane, _ = _plane(max_depth=2)
        _miss(plane, make_pod("b1", labels=BATCH), ["n1"])
        _miss(plane, make_pod("b2", labels=BATCH), ["n1"])
        # a batch arrival ranks no better than the incumbents: IT sheds
        _miss(plane, make_pod("b3", labels=BATCH), ["n1"])
        snap = plane.snapshot()
        assert snap["depth"] == 2
        assert "default/b3" not in [e["pod"] for e in snap["queue"]]
        assert any(
            r["detail"]["event"] == "overflow_shed"
            and r["detail"]["pod"] == "default/b3"
            for r in _events(plane)
        )
        # a high arrival outranks the worst incumbent: b2 (latest
        # arrival of the worst class) sheds and h1 takes the slot
        _miss(plane, make_pod("h1", labels=HIGH), ["n1"])
        snap = plane.snapshot()
        assert [e["pod"] for e in snap["queue"]] == [
            "default/h1",
            "default/b1",
        ]
        assert _counter(
            plane,
            "pas_admission_rejected_total",
            **{"class": "batch", "reason": "overflow"},
        ) == 2.0

    def test_fairness_streak_lets_the_waiting_class_through(self):
        plane, _ = _plane(fairness_streak=2)
        _consult(plane, make_pod("h1", labels=HIGH), ["n1"])
        _consult(plane, make_pod("h2", labels=HIGH), ["n1"])
        assert plane.snapshot()["streak"] == {"class": "high", "count": 2}
        _miss(plane, make_gang_pod("h0", "g-h", 8, labels=dict(HIGH)),
              ["n1", "n2"])
        # the streak cap overrides the hold: batch gets one through
        assert _consult(plane, make_pod("b1", labels=BATCH), ["n1"]) is None
        assert any(
            r["detail"]["event"] == "fairness"
            and r["detail"]["pod"] == "default/b1"
            for r in _events(plane)
        )
        # ...exactly one: the streak reset to (batch, 1), so the next
        # batch pod waits its turn again
        assert _consult(
            plane, make_pod("b2", labels=BATCH), ["n1"]
        ) is not None

    def test_backfill_needs_spare_nodes_beyond_head_demand(self):
        plane, _ = _plane()
        _miss(plane, make_gang_pod("h0", "g-h", 2, "1x2",
                                   labels=dict(HIGH)), ["n1", "n2"])
        # 2 eligible - 2 unmet head demand < 1: admitting would eat the
        # gang's window — hold
        assert _consult(
            plane, make_pod("b1", labels=BATCH), ["n1", "n2"]
        ) is not None
        # 3 eligible - 2 leaves one spare: backfill
        assert _consult(
            plane, make_pod("b2", labels=BATCH), ["n1", "n2", "n3"]
        ) is None
        assert _counter(
            plane, "pas_admission_backfill_total", **{"class": "batch"}
        ) == 1.0

    def test_backfill_when_head_holds_a_reservation(self):
        class _GangStub:
            def gang_state(self, gang_id):
                return "reserved"

        plane, _ = _plane()
        plane.gangs = _GangStub()
        _miss(plane, make_gang_pod("h0", "g-h", 8, "2x4",
                                   labels=dict(HIGH)), ["n1", "n2"])
        # the head's demand is covered by its slice (the overlay keeps
        # every reserved node out of this pod's eligible set), so even
        # one spare node backfills
        assert _consult(plane, make_pod("b1", labels=BATCH), ["n1"]) is None
        assert _counter(
            plane, "pas_admission_backfill_total", **{"class": "batch"}
        ) == 1.0

    def test_starvation_counts_past_the_consult_threshold(self):
        plane, _ = _plane(starve_consults=2)
        pod = make_pod("b1", labels=BATCH)
        _miss(plane, pod, ["n1"])  # enqueue
        _miss(plane, pod, ["n1"])  # consult 1: aging, not yet starved
        assert _counter(
            plane, "pas_admission_starved_total", **{"class": "batch"}
        ) == 0.0
        _miss(plane, pod, ["n1"])  # consult 2: at the threshold
        _miss(plane, pod, ["n1"])  # consult 3: every one counts now
        assert _counter(
            plane, "pas_admission_starved_total", **{"class": "batch"}
        ) == 2.0

    def test_terminal_failure_drops_the_queued_entry(self):
        plane, _ = _plane()
        pod = make_pod("b1", labels=BATCH)
        _miss(plane, pod, ["n1"])
        assert plane.snapshot()["depth"] == 1
        _miss(plane, pod, ["n1"], code=decisions.CODE_RULE_VIOLATION)
        assert plane.snapshot()["depth"] == 0
        assert _counter(
            plane,
            "pas_admission_rejected_total",
            **{"class": "batch", "reason": "terminal"},
        ) == 1.0
        assert any(
            r["detail"]["event"] == "terminal" for r in _events(plane)
        )

    def test_terminal_failure_never_enqueues(self):
        plane, _ = _plane()
        _miss(plane, make_pod("b1", labels=BATCH), ["n1"],
              code=decisions.CODE_FAIL_CLOSED)
        assert plane.snapshot()["depth"] == 0

    def test_bind_feedback_clears_the_entry(self):
        plane, _ = _plane()
        _miss(plane, make_pod("b1", labels=BATCH), ["n1"])
        plane.observe_bind("default", "b1")
        assert plane.snapshot()["depth"] == 0
        assert plane.counters.get(
            "pas_admission_queue_depth",
            kind="gauge",
            labels={"class": "batch"},
        ) == 0.0

    def test_snapshot_carries_cumulative_counters(self):
        plane, _ = _plane()
        _miss(plane, make_pod("b1", labels=BATCH), ["n1"])
        _consult(plane, make_pod("h1", labels=HIGH), ["n1"])
        counters = plane.snapshot()["counters"]
        assert counters["queued"] == 1.0
        assert counters["admitted"] == 1.0
        assert counters["preemptions"] == 0.0


# ---------------------------------------------------------------------------
# victim selection + atomic execution
# ---------------------------------------------------------------------------


def _preemption_world(
    max_victims=8, leader=None, actuator_mode=MODE_ACTIVE, retry_s=0.0
):
    """A 4x4 mesh with a real tracker + fake kube behind the planner."""
    kube = FakeKubeClient()
    kube.add_mesh(4, 4)
    clock = _Clock()
    tracker = GangTracker(
        nodes_provider=kube.list_nodes,
        pods_provider=kube.list_pods,
        ttl_s=600.0,
        clock=clock.now,
    )
    plane, _ = _plane(clock=clock.now)
    plane.gangs = tracker
    actuator = SafeActuator(
        kube,
        mode=actuator_mode,
        rate_per_s=1000.0,
        burst=100,
        cooldown_s=0.0,
        clock=clock.now,
    )
    planner = PreemptionPlanner(
        plane,
        tracker,
        actuator,
        max_victims=max_victims,
        retry_s=retry_s,
        leadership=leader,
        clock=clock.now,
    )
    plane.preemption = planner
    return kube, tracker, plane, planner, clock


def _place_gang(kube, tracker, plane, group, size, topo, klass, rows):
    """Reserve + bind one gang onto ``rows`` of the mesh, landing a
    Running pod per member (the kube-scheduler's side of Bind)."""
    labels = {shared_labels.PRIORITY_LABEL: klass}
    candidates = [f"mesh-{r}-{c}" for r in rows for c in range(4)]
    for i in range(size):
        pod = make_gang_pod(
            f"{group}-{i}", group, size, topo, labels=dict(labels)
        )
        _consult(plane, pod, candidates)  # the plane learns the class
        failed, _codes = tracker.filter_overlay(pod, list(candidates))
        passing = [n for n in candidates if n not in failed]
        assert passing, f"{group} member {i} found no slice"
        taken = {p.spec_node_name for p in kube.list_pods()}
        node = next(n for n in passing if n not in taken)
        tracker.observe_bind(pod.namespace, pod.name, node)
        kube.add_pod(
            make_pod(
                pod.name,
                labels=dict(pod.get_labels()),
                node_name=node,
                phase="Running",
            )
        )
    assert tracker.gang_state(f"default/{group}") == "bound"


def _target_pod(name="t-0", group="g-target"):
    return make_gang_pod(name, group, 8, "2x4", labels=dict(HIGH))


class TestVictimSelection:
    def test_never_preempts_equal_or_higher_class(self):
        kube, tracker, plane, planner, _ = _preemption_world()
        _place_gang(kube, tracker, plane, "high-a", 8, "2x4", "high",
                    (0, 1))
        _place_gang(kube, tracker, plane, "high-b", 8, "2x4", "high",
                    (2, 3))
        assert planner.maybe_preempt(_target_pod(), "high", 0) is False
        assert kube.evictions == []
        assert _counter(
            plane, "pas_preemption_plans_total", outcome="infeasible"
        ) == 1.0
        assert _counter(plane, "pas_preemption_reservations_total") == 0.0

    def test_whole_gang_evicted_and_slice_reserved_while_draining(self):
        kube, tracker, plane, planner, _ = _preemption_world()
        _place_gang(kube, tracker, plane, "high-a", 8, "2x4", "high",
                    (0, 1))
        _place_gang(kube, tracker, plane, "batch-a", 8, "2x4", "batch",
                    (2, 3))
        pod = _target_pod()
        assert planner.maybe_preempt(pod, "high", 0) is True
        # whole gang, nothing else: all 8 batch members, zero high
        evicted = sorted(e["pod"] for e in kube.evictions)
        assert evicted == sorted(f"batch-a-{i}" for i in range(8))
        # reservation-while-draining: the victim keeps DRAINING state
        # (its nodes stay accounted) and the target already holds the
        # slice before a single victim pod is actually gone
        assert tracker.gang_state("default/batch-a") == "draining"
        assert tracker.gang_state("default/g-target") == "reserved"
        assert _counter(plane, "pas_preemption_reservations_total") == 1.0
        # provenance: the record names target, victims, and the slice
        records = _events(plane, verb="preemption")
        assert len(records) == 1
        detail = records[0]["detail"]
        assert detail["target_gang"] == "default/g-target"
        assert [v["class"] for v in detail["victims"]] == ["batch"]
        assert len(detail["reserved_nodes"]) == 8

    def test_survivor_gang_untouched(self):
        kube, tracker, plane, planner, _ = _preemption_world()
        _place_gang(kube, tracker, plane, "high-a", 8, "2x4", "high",
                    (0, 1))
        _place_gang(kube, tracker, plane, "batch-a", 8, "2x4", "batch",
                    (2, 3))
        planner.maybe_preempt(_target_pod(), "high", 0)
        assert tracker.gang_state("default/high-a") == "bound"
        survivors = [
            p.name
            for p in kube.list_pods()
            if p.name.startswith("high-a-") and p.phase == "Running"
        ]
        assert len(survivors) == 8

    def test_refusal_aborts_with_no_reservation(self):
        """Fenced-refusal containment: a refused actuation (here the
        mode gate, the same pre-flight that fencing/rate/cooldown
        refusals share) aborts the plan and creates NO reservation —
        nothing is admitted on the back of a half-executed plan."""
        kube, tracker, plane, planner, _ = _preemption_world(
            actuator_mode=MODE_DRY_RUN
        )
        _place_gang(kube, tracker, plane, "batch-a", 8, "2x4", "batch",
                    (0, 1))
        assert planner.maybe_preempt(_target_pod(), "high", 0) is False
        assert kube.evictions == []
        assert tracker.gang_state("default/batch-a") == "bound"
        assert tracker.gang_state("default/g-target") not in (
            "reserved", "bound", "draining",
        )
        assert _counter(plane, "pas_preemption_reservations_total") == 0.0
        assert _counter(
            plane,
            "pas_preemption_plans_total",
            outcome="actuation_refused",
        ) == 1.0
        assert _events(plane, verb="preemption") == []

    def test_bounded_appetite_refuses_oversized_plans(self):
        kube, tracker, plane, planner, _ = _preemption_world(max_victims=4)
        _place_gang(kube, tracker, plane, "batch-a", 8, "2x4", "batch",
                    (0, 1))
        _place_gang(kube, tracker, plane, "batch-b", 8, "2x4", "batch",
                    (2, 3))
        assert planner.maybe_preempt(_target_pod(), "high", 0) is False
        assert kube.evictions == []
        assert _counter(
            plane, "pas_preemption_plans_total", outcome="over_budget"
        ) == 1.0

    def test_standby_never_plans(self):
        kube, tracker, plane, planner, _ = _preemption_world(
            leader=_Leader(False)
        )
        _place_gang(kube, tracker, plane, "batch-a", 8, "2x4", "batch",
                    (0, 1))
        assert planner.maybe_preempt(_target_pod(), "high", 0) is False
        assert kube.evictions == []
        assert _counter(
            plane, "pas_preemption_plans_total", outcome="not_leader"
        ) == 1.0

    def test_retry_throttle_bounds_replanning(self):
        kube, tracker, plane, planner, clock = _preemption_world(
            retry_s=30.0
        )
        _place_gang(kube, tracker, plane, "high-a", 8, "2x4", "high",
                    (0, 1))
        _place_gang(kube, tracker, plane, "high-b", 8, "2x4", "high",
                    (2, 3))
        planner.maybe_preempt(_target_pod(), "high", 0)
        planner.maybe_preempt(_target_pod(), "high", 0)  # throttled
        assert _counter(
            plane, "pas_preemption_plans_total", outcome="infeasible"
        ) == 1.0
        clock.advance(31.0)
        planner.maybe_preempt(_target_pod(), "high", 0)
        assert _counter(
            plane, "pas_preemption_plans_total", outcome="infeasible"
        ) == 2.0


# ---------------------------------------------------------------------------
# flag wiring
# ---------------------------------------------------------------------------


class TestFlagWiring:
    def _tas_args(self, argv):
        from platform_aware_scheduling_tpu.cmd import common, tas

        parser = tas.build_arg_parser()
        args = parser.parse_args(argv)
        common.validate_admission_flags(parser, args)
        return args

    def test_preemption_requires_admission(self, capsys):
        with pytest.raises(SystemExit) as exc:
            self._tas_args(["--preemption", "on", "--gang", "on"])
        assert exc.value.code == 2
        assert "--admission=on" in capsys.readouterr().err

    def test_preemption_requires_gang(self, capsys):
        with pytest.raises(SystemExit) as exc:
            self._tas_args(["--admission", "on", "--preemption", "on"])
        assert exc.value.code == 2
        assert "--gang=on" in capsys.readouterr().err

    def test_full_stack_validates(self):
        args = self._tas_args(
            ["--admission", "on", "--preemption", "on", "--gang", "on"]
        )
        assert args.preemptionMaxVictims == 8

    def test_malformed_ladder_exits(self):
        with pytest.raises(SystemExit) as exc:
            self._tas_args(
                ["--admission", "on", "--admissionClasses", "high,high"]
            )
        assert exc.value.code == 2
        with pytest.raises(SystemExit) as exc:
            self._tas_args(
                ["--admission", "on", "--admissionDefaultClass", "gold"]
            )
        assert exc.value.code == 2

    def test_gas_offers_no_preemption_flag(self):
        from platform_aware_scheduling_tpu.cmd import gas

        with pytest.raises(SystemExit) as exc:
            gas.build_arg_parser().parse_args(["--preemption", "on"])
        assert exc.value.code == 2
        # ...but the queue-only admission surface is there
        args = gas.build_arg_parser().parse_args(["--admission", "on"])
        assert args.admission == "on"

    def test_off_builds_nothing(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        args = tas.build_arg_parser().parse_args([])
        assert args.admission == "off"
        ext, _kube, _names = build_mesh_service(2, 2, gang=False)
        assert common.build_admission_plane(args, ext) is None
        assert ext.admission is None
        assert "pas_admission_" not in ext.metrics_text()

    def test_on_builds_plane_and_planner(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        args = tas.build_arg_parser().parse_args(
            ["--admission", "on", "--preemption", "on", "--gang", "on",
             "--admissionDepth", "7"]
        )
        ext, kube, _names = build_mesh_service(2, 2, gang=True)
        plane = common.build_admission_plane(
            args, ext, kube_client=kube, gang_tracker=ext.gangs
        )
        assert ext.admission is plane
        assert plane.classes == ("high", "normal", "batch")
        assert plane.max_depth == 7
        assert plane.gangs is ext.gangs
        assert plane.preemption is not None
        assert plane.preemption.actuator.mode == MODE_ACTIVE
        # the planner's actuator must NOT auto-release whole gangs (that
        # would fight reservation-while-draining)
        assert plane.preemption.actuator.gang_tracker is None

    def test_queue_only_without_tracker(self):
        from platform_aware_scheduling_tpu.cmd import common, gas

        args = gas.build_arg_parser().parse_args(["--admission", "on"])
        ext, kube, _names = build_mesh_service(2, 2, gang=False)
        plane = common.build_admission_plane(args, ext, kube_client=kube)
        assert plane is not None
        assert plane.preemption is None


# ---------------------------------------------------------------------------
# the off path
# ---------------------------------------------------------------------------


class TestOffPathPins:
    def test_quiet_plane_serves_byte_identical(self):
        """The plane only ever substitutes one failure for another —
        with no contention (nothing queued) every verb response is
        byte-identical to a build without the plane."""
        ext_off, _k1, names = build_mesh_service(4, 4, gang=True)
        ext_on, _k2, _n2 = build_mesh_service(4, 4, gang=True)
        ext_on.admission, _ = _plane()
        ext_on.admission.gangs = ext_on.gangs
        single = {
            "metadata": {
                "name": "solo",
                "namespace": "default",
                "labels": {
                    "telemetry-policy": "gang-pol",
                    shared_labels.PRIORITY_LABEL: "high",
                },
            }
        }
        gang_member = {
            "metadata": {
                "name": "g-0",
                "namespace": "default",
                "labels": {
                    "telemetry-policy": "gang-pol",
                    shared_labels.GROUP_LABEL: "g-a",
                    shared_labels.GANG_SIZE_LABEL: "8",
                    shared_labels.GANG_TOPOLOGY_LABEL: "2x4",
                    shared_labels.PRIORITY_LABEL: "high",
                },
            }
        }
        for pod_obj in (single, gang_member):
            for verb in ("filter", "prioritize"):
                body = {"Pod": pod_obj, "NodeNames": list(names)}
                off = _post(ext_off, verb, body)
                on = _post(ext_on, verb, body)
                assert off.status == on.status
                assert off.body == on.body

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_debug_endpoint_404_off_200_on(self, serving):
        ext, _kube, _names = build_mesh_service(2, 2, gang=False)
        server = (
            start_async(ext) if serving == "async" else start_threaded(ext)
        )
        try:
            status, _h, body = get_request(server.port, "/debug/admission")
            assert status == 404
            status, _h, metrics = get_request(server.port, "/metrics")
            assert b"pas_admission_" not in metrics
            # wire the plane: same server, the route comes alive
            ext.admission, _ = _plane()
            _miss(ext.admission, make_pod("b1", labels=BATCH), ["n1"])
            status, _h, body = get_request(server.port, "/debug/admission")
            assert status == 200
            snap = json.loads(body)
            assert snap["enabled"] is True
            assert snap["depth"] == 1
            assert snap["counters"]["queued"] == 1.0
            status, _h, metrics = get_request(server.port, "/metrics")
            assert b"pas_admission_queued_total" in metrics
            assert b"pas_admission_queue_depth" in metrics
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# torus wraparound feasibility (ops/topology satellite)
# ---------------------------------------------------------------------------


class TestTorusFeasibility:
    def test_device_host_parity_byte_exact(self):
        rng = np.random.default_rng(16)
        for _ in range(20):
            m, n = rng.integers(2, 9, 2)
            free = rng.random((m, n)) < 0.6
            for h, w in [(1, 1), (2, 2), (2, 3), (int(m), int(n))]:
                device = topology.torus_feasibility_device(free, h, w)
                host = topology.torus_feasibility_host(free, h, w)
                for d_arr, h_arr in zip(device, host):
                    assert d_arr.dtype == h_arr.dtype
                    assert np.array_equal(d_arr, h_arr)

    def test_wraparound_window_feasible_only_on_the_torus(self):
        """Free columns 0 and 3 of a 4x4: two disconnected planar
        strips, but one contiguous 4x2 ring window across the seam."""
        free = np.zeros((4, 4), bool)
        free[:, 0] = True
        free[:, 3] = True
        planar = topology.topology_feasibility_host(free, 4, 2)
        assert not planar.anchor_ok.any()
        torus = topology.torus_feasibility_host(free, 4, 2)
        assert torus.anchor_ok[0, 3]
        cells = topology.torus_slice_cells(0, 3, 4, 2, 4, 4)
        assert all(free[i, j] for i, j in cells)
        assert len(set(cells)) == 8

    def test_window_larger_than_torus_self_overlaps(self):
        for fn in (
            topology.torus_feasibility_host,
            topology.torus_feasibility_device,
        ):
            feas = fn(np.ones((2, 2), bool), 3, 1)
            assert not feas.anchor_ok.any()


# ---------------------------------------------------------------------------
# acceptance: the twin scenarios over real sockets on BOTH front-ends
# ---------------------------------------------------------------------------


def _run_scenario(scenario, serving):
    """Drive one admission scenario tick by tick with a live front-end
    mounted; returns the /debug/admission snapshot and /metrics text
    read over the wire after the last tick."""
    scale = {"period_s": 5.0}
    twin = scenario.build(scale)
    server = twin.serve(serving)
    try:
        for t in range(scenario.ticks(scale)):
            scenario.apply(twin, t)
            twin.tick()
        failures = [c for c in scenario.checks(twin) if not c["ok"]]
        assert not failures, failures
        status, _h, body = get_request(server.port, "/debug/admission")
        assert status == 200
        status, _h, metrics = get_request(server.port, "/metrics")
        assert status == 200
        return json.loads(body), metrics.decode()
    finally:
        server.shutdown()
        twin.close()


class TestAdmissionScenarios:
    """ISSUE 16 acceptance: the three scenarios green over a real
    socket on both front-ends, with the wire's /debug/admission and
    /metrics agreeing with the in-process verdicts."""

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_priority_inversion_held_at_the_gate(self, serving):
        snap, metrics = _run_scenario(tw.PriorityInversionStorm(), serving)
        assert snap["counters"]["blocked"] > 0
        assert snap["counters"]["preemptions"] == 0
        assert snap["depth"] == 0  # everyone landed in the end
        assert "pas_admission_blocked_total" in metrics

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_backfill_without_starvation(self, serving):
        snap, metrics = _run_scenario(tw.BackfillStarvation(), serving)
        assert snap["counters"]["backfills"] > 0
        assert snap["counters"]["starved"] == 0
        assert "pas_admission_backfill_total" in metrics

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_preemption_cascade_admits_high_gang(self, serving):
        snap, metrics = _run_scenario(
            tw.PreemptionCascade(preemption=True), serving
        )
        assert snap["counters"]["preemptions"] == 1
        assert snap["preemption"]["last_plan"]["outcome"] == "planned"
        assert "pas_preemption_reservations_total" in metrics

    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_preemption_off_starves_without_evicting(self, serving):
        snap, _metrics = _run_scenario(
            tw.PreemptionCascade(preemption=False), serving
        )
        assert snap["counters"]["preemptions"] == 0
        assert snap["counters"]["starved"] > 0
        assert snap["preemption"] is None

    def test_head_to_head_verdict(self):
        result = tw.admission_headtohead()
        assert result["all_ok"], result
        assert result["strictly_better"]
        on = result["preemption_on"]
        off = result["preemption_off"]
        assert on["admitted"] and on["passed"] and off["passed"]
        assert on["budget"] > off["budget"]
        assert result["diurnal_quiet"]["ok"]

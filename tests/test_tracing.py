"""utils/tracing.py primitives: nearest-rank quantiles, counter/gauge
disambiguation, thread-safety under concurrent observe/inc, empty-label
dumps, window rollover, and real Prometheus exposition (round-tripped
through the in-tree parser, utils/trace.py)."""

import threading

import pytest

from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.tracing import (
    CounterSet,
    LatencyRecorder,
    histograms_text,
    quantile,
)


class TestQuantile:
    def test_nearest_rank_p99_of_100(self):
        """p99 of 100 samples is the 99th value (index 98) — the old
        int(q*n) indexing overshot to the clamped max."""
        values = [float(i) for i in range(1, 101)]  # 1..100
        assert quantile(values, 0.99) == 99.0
        assert quantile(values, 0.50) == 50.0
        assert quantile(values, 0.90) == 90.0

    def test_small_windows(self):
        assert quantile([1.0, 2.0, 3.0, 4.0], 0.50) == 2.0  # ceil(2)=2nd
        assert quantile([1.0, 2.0, 3.0, 4.0], 0.99) == 4.0
        assert quantile([7.0], 0.99) == 7.0
        assert quantile([7.0], 0.01) == 7.0

    def test_edges(self):
        assert quantile([], 0.99) == 0.0
        values = [1.0, 2.0, 3.0]
        assert quantile(values, 0.0) == 1.0
        assert quantile(values, 1.0) == 3.0
        # q past 1 clamps to the max, never out of range
        assert quantile(values, 1.5) == 3.0

    def test_p99_not_max_for_large_samples(self):
        """With 200 samples and one outlier, p99 (198th value) must NOT
        collapse to the outlier max."""
        values = [1.0] * 199 + [100.0]
        assert quantile(values, 0.99) == 1.0
        assert max(values) == 100.0


class TestCounterSet:
    def test_counter_gauge_name_collision(self):
        cs = CounterSet()
        cs.inc("pas_thing", 3)
        cs.set_gauge("pas_thing", 99.5)
        assert cs.get("pas_thing", kind="counter") == 3
        assert cs.get("pas_thing", kind="gauge") == 99.5
        # historical precedence without kind: the counter wins
        assert cs.get("pas_thing") == 3
        with pytest.raises(ValueError):
            cs.get("pas_thing", kind="bogus")

    def test_missing_names_read_zero(self):
        cs = CounterSet()
        assert cs.get("nope") == 0
        assert cs.get("nope", kind="counter") == 0
        assert cs.get("nope", kind="gauge") == 0

    def test_float_increments(self):
        cs = CounterSet()
        cs.inc("pas_seconds_total", 0.25)
        cs.inc("pas_seconds_total", 0.5)
        assert cs.get("pas_seconds_total") == 0.75

    def test_exposition_types_and_collision_validity(self):
        """A counter/gauge name collision must still render as VALID
        exposition (one TYPE line per name)."""
        cs = CounterSet()
        cs.inc("pas_a_total", 2)
        cs.set_gauge("pas_depth", 7)
        cs.inc("pas_clash", 1)
        cs.set_gauge("pas_clash", 5)
        text = cs.prometheus_text(help_texts={"pas_a_total": "a things"})
        fams = trace.parse_prometheus_text(text)
        assert fams["pas_a_total"]["type"] == "counter"
        assert fams["pas_a_total"]["help"] == "a things"
        assert fams["pas_depth"]["type"] == "gauge"
        assert fams["pas_clash"]["type"] == "counter"
        assert text.count("pas_clash") == 2  # one TYPE + one sample

    def test_empty_dump(self):
        assert CounterSet().prometheus_text() == ""

    def test_labeled_series_accumulate_and_sum(self):
        cs = CounterSet()
        cs.inc("pas_evals_total", labels={"strategy": "dontschedule"})
        cs.inc("pas_evals_total", 2, labels={"strategy": "deschedule"})
        cs.inc("pas_evals_total", labels={"strategy": "dontschedule"})
        assert cs.get(
            "pas_evals_total", labels={"strategy": "dontschedule"}
        ) == 2
        assert cs.get(
            "pas_evals_total", labels={"strategy": "deschedule"}
        ) == 2
        # labels=None sums every series of the family
        assert cs.get("pas_evals_total") == 4
        # missing series reads zero
        assert cs.get("pas_evals_total", labels={"strategy": "nope"}) == 0

    def test_labeled_exposition_round_trips(self):
        cs = CounterSet()
        cs.inc("pas_evals_total", 3, labels={"strategy": "dontschedule"})
        cs.inc("pas_evals_total", 1, labels={"strategy": "deschedule"})
        cs.set_gauge("pas_age_seconds", 1.5, labels={"metric": "cpu"})
        cs.set_gauge("pas_age_seconds", 0.5, labels={"metric": "mem"})
        cs.set_gauge("pas_plain", 7)
        text = cs.prometheus_text(help_texts={"pas_evals_total": "evals"})
        fams = trace.parse_prometheus_text(text)
        # one TYPE line per family, one sample per label set
        assert text.count("# TYPE pas_evals_total") == 1
        samples = {
            labels.get("strategy"): value
            for _n, labels, value in fams["pas_evals_total"]["samples"]
        }
        assert samples == {"dontschedule": 3, "deschedule": 1}
        ages = {
            labels.get("metric"): value
            for _n, labels, value in fams["pas_age_seconds"]["samples"]
        }
        assert ages == {"cpu": 1.5, "mem": 0.5}
        assert fams["pas_plain"]["samples"][0][2] == 7

    def test_label_values_escape(self):
        cs = CounterSet()
        tricky = 'quo"te\\back\nnewline'
        cs.set_gauge("pas_esc", 1, labels={"metric": tricky})
        fams = trace.parse_prometheus_text(cs.prometheus_text())
        (_name, labels, value) = fams["pas_esc"]["samples"][0]
        assert labels["metric"] == tricky
        assert value == 1

    def test_remove_drops_series_from_exposition(self):
        cs = CounterSet()
        cs.set_gauge("pas_age_seconds", 1.0, labels={"metric": "gone"})
        cs.set_gauge("pas_age_seconds", 2.0, labels={"metric": "kept"})
        cs.remove("pas_age_seconds", labels={"metric": "gone"}, kind="gauge")
        fams = trace.parse_prometheus_text(cs.prometheus_text())
        metrics = {
            labels["metric"] for _n, labels, _v
            in fams["pas_age_seconds"]["samples"]
        }
        assert metrics == {"kept"}
        # removing the last series drops the family (no orphan TYPE line)
        cs.remove("pas_age_seconds", labels={"metric": "kept"})
        assert cs.prometheus_text() == ""
        cs.remove("pas_never", labels={"metric": "x"})  # no-op, no raise

    def test_evicted_metric_age_gauge_is_removed(self):
        """tas/cache.delete_metric evicting the last ref drops the
        metric's age-gauge series from the exposition."""
        from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
        from platform_aware_scheduling_tpu.tas.metrics import DummyMetricsClient
        from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
        from platform_aware_scheduling_tpu.utils.quantity import Quantity

        counters = CounterSet()
        cache = AutoUpdatingCache(counters=counters)
        cache.write_metric("doomed", None)
        client = DummyMetricsClient(
            {"doomed": {"n": NodeMetric(value=Quantity(1))}}
        )
        cache.update_all_metrics(client)
        assert "doomed" in counters.prometheus_text()
        cache.delete_metric("doomed")
        assert "doomed" not in counters.prometheus_text()

    def test_labeled_and_unlabeled_series_coexist(self):
        cs = CounterSet()
        cs.inc("pas_mixed_total")
        cs.inc("pas_mixed_total", 5, labels={"kind": "x"})
        assert cs.get("pas_mixed_total") == 6
        fams = trace.parse_prometheus_text(cs.prometheus_text())
        assert len(fams["pas_mixed_total"]["samples"]) == 2


class TestLatencyRecorder:
    def test_empty_label_dumps(self):
        rec = LatencyRecorder()
        assert rec.prometheus_text() == ""
        assert rec.labels() == []
        summary = rec.summary("never_observed")
        assert summary["count"] == 0
        assert summary["p99"] == 0.0
        assert summary["max"] == 0.0

    def test_window_rollover(self):
        """Counts/sums keep the full history; the quantile window is
        bounded and rolls to the most recent samples."""
        rec = LatencyRecorder(window=8)
        for i in range(20):
            rec.observe("verb", float(i))
        s = rec.summary("verb")
        assert s["count"] == 20
        # window holds 12..19 only: p50 = nearest-rank 4th of 8 = 15
        assert s["p50"] == 15.0
        assert s["max"] == 19.0
        assert s["mean"] == pytest.approx(sum(range(20)) / 20)

    def test_concurrent_observe_and_inc(self):
        """N threads hammering observe()/inc() concurrently lose nothing:
        totals are exact afterward."""
        rec = LatencyRecorder()
        cs = CounterSet()
        threads_n, per_thread = 8, 500
        barrier = threading.Barrier(threads_n)

        def worker(k):
            barrier.wait(10)
            for i in range(per_thread):
                rec.observe(f"label_{k % 2}", 0.001)
                cs.inc("pas_total")
                cs.set_gauge("pas_gauge", i)

        threads = [
            threading.Thread(target=worker, args=(k,))
            for k in range(threads_n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        total = sum(rec.summary(lbl)["count"] for lbl in rec.labels())
        assert total == threads_n * per_thread
        assert cs.get("pas_total") == threads_n * per_thread
        # the exposition renders while the structures are warm
        fams = trace.parse_prometheus_text(
            rec.prometheus_text() + cs.prometheus_text()
        )
        count_samples = {
            labels["verb"]: value
            for name, labels, value in fams["pas_request_duration_seconds"][
                "samples"
            ]
            if name.endswith("_count")
        }
        assert sum(count_samples.values()) == threads_n * per_thread

    def test_histogram_merge_single_family(self):
        """Several recorders render under ONE # TYPE header with their
        shared labels summed — never duplicate family headers."""
        a, b = LatencyRecorder(), LatencyRecorder()
        a.observe("x", 0.001)
        a.observe("shared", 0.001)
        b.observe("shared", 0.002)
        text = histograms_text([a, b], help_texts=trace.help_texts())
        assert text.count("# TYPE pas_request_duration_seconds") == 1
        fams = trace.parse_prometheus_text(text)
        counts = {
            labels["verb"]: value
            for name, labels, value in fams["pas_request_duration_seconds"][
                "samples"
            ]
            if name.endswith("_count")
        }
        assert counts == {"x": 1, "shared": 2}


class TestPrometheusParser:
    """The in-tree text-format parser rejects what a real scraper would."""

    def test_rejects_duplicate_type(self):
        with pytest.raises(ValueError, match="duplicate TYPE"):
            trace.parse_prometheus_text(
                "# TYPE pas_x counter\n# TYPE pas_x gauge\npas_x 1\n"
            )

    def test_rejects_duplicate_series(self):
        with pytest.raises(ValueError, match="duplicate series"):
            trace.parse_prometheus_text("pas_x 1\npas_x 2\n")

    def test_rejects_bad_value(self):
        with pytest.raises(ValueError, match="bad value"):
            trace.parse_prometheus_text("pas_x nope\n")

    def test_rejects_missing_inf_bucket(self):
        bad = (
            "# TYPE pas_h histogram\n"
            'pas_h_bucket{le="1"} 1\n'
            "pas_h_sum 1\npas_h_count 1\n"
        )
        with pytest.raises(ValueError, match="missing \\+Inf"):
            trace.parse_prometheus_text(bad)

    def test_rejects_non_cumulative_buckets(self):
        bad = (
            "# TYPE pas_h histogram\n"
            'pas_h_bucket{le="1"} 5\n'
            'pas_h_bucket{le="2"} 3\n'
            'pas_h_bucket{le="+Inf"} 5\n'
        )
        with pytest.raises(ValueError, match="non-cumulative"):
            trace.parse_prometheus_text(bad)

    def test_parses_escaped_labels(self):
        fams = trace.parse_prometheus_text(
            'pas_x{verb="a\\"b\\\\c"} 2.5\n'
        )
        ((name, labels, value),) = fams["pas_x"]["samples"]
        assert labels == {"verb": 'a"b\\c'}
        assert value == 2.5

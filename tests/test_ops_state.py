"""TensorStateMirror: cache-hook sync, interning, capacity growth,
policy compilation, host-only fallback marking."""

import numpy as np

from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.rules import (
    OP_GREATER_THAN,
    OP_LESS_THAN,
)
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def info(**kv):
    return {node: NodeMetric(value=Quantity(v)) for node, v in kv.items()}


def attach_pair():
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror(node_capacity=4, metric_capacity=2)
    mirror.attach(cache)
    return cache, mirror


def test_metric_write_lands_in_matrix():
    cache, mirror = attach_pair()
    cache.write_metric("health", info(node1="10", node2="3500m"))
    view = mirror.device_view()
    row = 0
    i1, i2 = view.node_index["node1"], view.node_index["node2"]
    vals = i64.to_int64_np(view.values)
    assert vals[row, i1] == 10_000  # milli-units
    assert vals[row, i2] == 3500
    present = np.asarray(view.present)
    assert present[row, i1] and present[row, i2]
    assert not present[row].sum() > 2


def test_view_memoized_until_mutation():
    cache, mirror = attach_pair()
    cache.write_metric("m", info(a="1"))
    v1 = mirror.device_view()
    assert mirror.device_view() is v1
    cache.write_metric("m", info(a="2"))
    v2 = mirror.device_view()
    assert v2 is not v1
    # old snapshot untouched (copy-on-write)
    assert i64.to_int64_np(v1.values)[0, v1.node_index["a"]] == 1000


def test_node_capacity_growth():
    cache, mirror = attach_pair()
    cache.write_metric("m", info(**{f"n{i}": str(i) for i in range(20)}))
    view = mirror.device_view()
    assert view.node_capacity >= 20
    vals = i64.to_int64_np(view.values)
    for i in range(20):
        assert vals[0, view.node_index[f"n{i}"]] == i * 1000


def test_metric_capacity_growth_and_row_reuse():
    cache, mirror = attach_pair()
    for m in ["m0", "m1", "m2", "m3", "m4"]:
        cache.write_metric(m, info(a="1"))
    # register (refcount) then delete m2 -> its row is freed and reused
    cache.write_metric("m2")
    cache.delete_metric("m2")
    cache.write_metric("m9", info(a="9"))
    view = mirror.device_view()
    vals = i64.to_int64_np(view.values)
    present = np.asarray(view.present)
    col = view.node_index["a"]
    live_rows = present[:, col].sum()
    assert live_rows == 5  # m0,m1,m3,m4,m9
    assert 9000 in vals[:, col]


def test_candidate_mask_and_unknown_nodes():
    cache, mirror = attach_pair()
    cache.write_metric("m", info(a="1", b="2"))
    view = mirror.device_view()
    mask, unknown = view.candidate_mask(["a", "ghost", "b"])
    assert unknown == ["ghost"]
    m = np.asarray(mask)
    assert m[view.node_index["a"]] and m[view.node_index["b"]]
    assert m.sum() == 2


def test_policy_compilation():
    cache, mirror = attach_pair()
    cache.write_metric("cpu", info(a="1"))
    policy = TASPolicy.from_obj(
        make_policy(
            "p1",
            strategies={
                "dontschedule": [rule("cpu", "GreaterThan", 80)],
                "scheduleonmetric": [rule("mem", "LessThan", 0)],
            },
        )
    )
    cache.write_policy("default", "p1", policy)
    compiled = mirror.policy("default", "p1")
    assert compiled is not None
    rs = compiled.device_rules("dontschedule")
    assert rs is not None
    assert int(rs.op_id[0]) == OP_GREATER_THAN
    assert i64.to_int64_np(rs.target)[0] == 80_000
    assert bool(rs.active[0]) and not bool(rs.active[1])
    assert compiled.scheduleonmetric_op == OP_LESS_THAN
    # the scheduleonmetric metric got interned even before any values
    view = mirror.device_view()
    assert compiled.scheduleonmetric_row >= 0


def test_unknown_operator_marks_host_only():
    cache, mirror = attach_pair()
    policy = TASPolicy.from_obj(
        make_policy("p", strategies={"dontschedule": [rule("m", "Weird", 1)]})
    )
    cache.write_policy("default", "p", policy)
    compiled = mirror.policy("default", "p")
    assert compiled.dontschedule.host_only
    assert compiled.device_rules("dontschedule") is None


def test_inexact_quantity_marks_metric_host_only():
    cache, mirror = attach_pair()
    # 1/3000 has no exact milli representation
    cache.write_metric("m", {"a": NodeMetric(value=Quantity("333333n"))})
    assert mirror.metric_host_only("m")
    cache.write_metric("m", info(a="5"))
    assert not mirror.metric_host_only("m")


def test_policy_delete_removes_compiled():
    cache, mirror = attach_pair()
    policy = TASPolicy.from_obj(
        make_policy("p", strategies={"dontschedule": [rule("m", "LessThan", 1)]})
    )
    cache.write_policy("default", "p", policy)
    assert mirror.policy("default", "p") is not None
    cache.delete_policy("default", "p")
    assert mirror.policy("default", "p") is None


class TestDescheduleDevicePath:
    def _setup(self, rules_list):
        from platform_aware_scheduling_tpu.tas.strategies import deschedule

        cache, mirror = attach_pair()
        policy = TASPolicy.from_obj(
            make_policy("desched-pol", strategies={"deschedule": rules_list})
        )
        cache.write_policy("default", "desched-pol", policy)
        strat = deschedule.Strategy.from_policy_strategy(
            policy.strategies["deschedule"]
        )
        strat.set_policy_name("desched-pol")
        return cache, mirror, strat

    def test_device_matches_host(self):
        import numpy as np

        rng = np.random.default_rng(11)
        cache, mirror, strat = self._setup(
            [rule("mem", "GreaterThan", 90), rule("disk", "LessThan", 10)]
        )
        names = [f"n{i}" for i in range(40)]
        cache.write_metric(
            "mem", info(**{n: str(int(rng.integers(0, 120))) for n in names})
        )
        cache.write_metric(
            "disk",
            info(**{n: str(int(rng.integers(0, 30))) for n in names[5:]}),
        )
        host = strat.violated(cache)
        device = strat.violated_device(mirror)
        assert device is not None
        assert set(device) == set(host)

    def test_mismatched_rules_fall_back(self):
        from platform_aware_scheduling_tpu.tas.strategies import deschedule

        cache, mirror, strat = self._setup([rule("mem", "GreaterThan", 90)])
        # a stale strategy instance with different rules must refuse device
        stale = deschedule.Strategy(
            policy_name="desched-pol",
            rules=[TASPolicy.from_obj(
                make_policy("x", strategies={"deschedule": [
                    rule("mem", "GreaterThan", 50)]})
            ).strategies["deschedule"].rules[0]],
        )
        assert stale.violated_device(mirror) is None

    def test_unknown_policy_falls_back(self):
        from platform_aware_scheduling_tpu.tas.strategies import deschedule

        _, mirror = attach_pair()
        strat = deschedule.Strategy(policy_name="ghost")
        assert strat.violated_device(mirror) is None


def test_unchanged_metric_rewrite_keeps_version():
    """Periodic refresh with identical values must not invalidate the
    snapshot (plans/device buffers stay valid in steady state)."""
    cache, mirror = attach_pair()
    cache.write_metric("m", info(a="1", b="2"))
    v1 = mirror.device_view()
    cache.write_metric("m", info(a="1", b="2"))  # same values, new objects
    assert mirror.device_view() is v1
    cache.write_metric("m", info(a="1"))  # b vanished -> real change
    assert mirror.device_view() is not v1

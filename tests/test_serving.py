"""The concurrent serving subsystem (serving/): event-loop front-end,
micro-batch coalescing, fused device warm, backpressure, and the c=8
concurrency bar the subsystem exists to meet (ISSUE 1 acceptance: async
c=8 p99 <= 3x c=1 with requests/s increasing, responses byte-identical
to the per-request path).

Everything here is hermetic: in-process servers on 127.0.0.1 ephemeral
ports, small synthetic clusters seeded exactly like benchmarks/http_load.
"""

import asyncio
import socket
import threading
import time

import pytest

from benchmarks.http_load import build_extender, drive, make_bodies
from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
)
from platform_aware_scheduling_tpu.serving import AsyncServer
from platform_aware_scheduling_tpu.serving.dispatcher import (
    MicroBatchDispatcher,
)


def _start_async(ext, **kwargs) -> AsyncServer:
    server = AsyncServer(
        ext, metrics_provider=ext.recorder.prometheus_text, **kwargs
    )
    server.start_server(port="0", unsafe=True, host="127.0.0.1", block=False)
    assert server.wait_ready(10)
    return server


def _raw_request(port: int, payload: bytes, timeout: float = 10.0):
    """(status, headers, body) for one POST over a fresh socket."""
    sock = socket.create_connection(("127.0.0.1", port), timeout=timeout)
    try:
        sock.sendall(payload)
        buf = bytearray()
        while b"\r\n\r\n" not in buf:
            chunk = sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("closed before header")
            buf += chunk
        head, _, rest = bytes(buf).partition(b"\r\n\r\n")
        lines = head.split(b"\r\n")
        status = int(lines[0].split(b" ", 2)[1])
        headers = {}
        length = 0
        for line in lines[1:]:
            name, _, value = line.partition(b":")
            headers[name.decode().lower()] = value.strip().decode()
            if name.lower() == b"content-length":
                length = int(value)
        body = bytearray(rest)
        while len(body) < length:
            chunk = sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("closed mid-body")
            body += chunk
        return status, headers, bytes(body[:length])
    finally:
        sock.close()


def _post(path: str, body: bytes, extra: str = "") -> bytes:
    return (
        f"POST {path} HTTP/1.1\r\nHost: t\r\n"
        f"Content-Type: application/json\r\n{extra}"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode() + body


class TestAsyncWireParity:
    """The async front-end keeps the threaded server's middleware and
    routing semantics (it literally routes through Server.route)."""

    @pytest.fixture(scope="class")
    def service(self):
        ext, names = build_extender(64, device=True)
        server = _start_async(ext)
        yield server, ext, names
        server.shutdown()

    def test_verb_roundtrip_matches_per_request_path(self, service):
        server, ext, names = service
        body = make_bodies(names, "nodenames", count=1)[0]
        status, _, got = _raw_request(
            server.port, _post("/scheduler/prioritize", body)
        )
        want = ext.prioritize(
            HTTPRequest(
                method="POST",
                path="/scheduler/prioritize",
                headers={"Content-Type": "application/json"},
                body=body,
            )
        )
        assert status == 200
        assert got == want.body

    def test_wrong_content_type_404(self, service):
        server, _, names = service
        body = make_bodies(names, "nodenames", count=1)[0]
        payload = (
            f"POST /scheduler/prioritize HTTP/1.1\r\nHost: t\r\n"
            f"Content-Type: application/json; charset=utf-8\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode() + body
        status, _, _ = _raw_request(server.port, payload)
        assert status == 404

    def test_non_post_405(self, service):
        server, _, _ = service
        payload = (
            b"PUT /scheduler/prioritize HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\nContent-Length: 0\r\n\r\n"
        )
        status, _, _ = _raw_request(server.port, payload)
        assert status == 405

    def test_unknown_path_404(self, service):
        server, _, _ = service
        status, _, _ = _raw_request(server.port, _post("/nope", b"{}"))
        assert status == 404

    def test_bad_framing_400(self, service):
        server, _, _ = service
        payload = (
            b"POST /scheduler/prioritize HTTP/1.1\r\nHost: t\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: 2\r\nContent-Length: 3\r\n\r\n{}"
        )
        status, _, _ = _raw_request(server.port, payload)
        assert status == 400

    def test_metrics_exposes_serving_stages(self, service):
        server, _, _ = service
        payload = b"GET /metrics HTTP/1.1\r\nHost: t\r\n\r\n"
        status, _, body = _raw_request(server.port, payload)
        assert status == 200
        text = body.decode()
        assert "pas_serving_requests_total" in text
        assert "pas_serving_queue_depth" in text
        assert 'verb="serving_batch_solve"' in text
        assert 'verb="serving_queue_wait"' in text

    def test_keep_alive_pipelining(self, service):
        server, _, names = service
        body = make_bodies(names, "nodenames", count=1)[0]
        req = _post("/scheduler/prioritize", body)
        sock = socket.create_connection(("127.0.0.1", server.port), timeout=10)
        try:
            sock.sendall(req + req)  # two pipelined requests
            buf = bytearray()
            deadline = time.time() + 10
            while buf.count(b"HTTP/1.1 200 OK") < 2 and time.time() < deadline:
                chunk = sock.recv(1 << 16)
                if not chunk:
                    break
                buf += chunk
            assert buf.count(b"HTTP/1.1 200 OK") == 2
        finally:
            sock.close()


class TestCoalescing:
    def test_n_concurrent_requests_one_batch_byte_identical(self):
        """N concurrent prioritize requests inside one window -> ONE
        dispatcher batch, responses byte-identical to the per-request
        path (the coalescing satellite)."""
        n = 6
        ext, names = build_extender(96, device=True)
        # a generous window so all barrier-released clients coalesce
        server = _start_async(ext, window_s=0.25, max_batch=64)
        try:
            bodies = make_bodies(names, "nodenames", count=n)
            # warm once (connection setup, caches) then snapshot counters
            _raw_request(
                server.port, _post("/scheduler/prioritize", bodies[0])
            )
            batches_before = server.batch.batches
            requests_before = server.counters.get(
                "pas_serving_batched_requests_total"
            )
            barrier = threading.Barrier(n)
            results = [None] * n
            errors = []

            def client(i):
                try:
                    barrier.wait(5)
                    results[i] = _raw_request(
                        server.port, _post("/scheduler/prioritize", bodies[i])
                    )
                except Exception as exc:  # pragma: no cover - diagnostics
                    errors.append(exc)

            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(15)
            assert not errors
            assert server.batch.batches == batches_before + 1
            assert (
                server.counters.get("pas_serving_batched_requests_total")
                - requests_before
                == n
            )
            # byte parity with the per-request path, per member
            for i in range(n):
                status, _, got = results[i]
                want = ext.prioritize(
                    HTTPRequest(
                        method="POST",
                        path="/scheduler/prioritize",
                        headers={"Content-Type": "application/json"},
                        body=bodies[i],
                    )
                )
                assert status == 200
                assert got == want.body
        finally:
            server.shutdown()

    def test_fused_warm_is_one_device_solve(self):
        """warm_batch seeds every ranking the batch needs in ONE batched
        kernel call, with cache entries identical to the per-pair path."""
        import numpy as np

        ext, names = build_extender(48, device=True)
        policy = ext.cache.read_policy("default", "load-pol")
        compiled, view = ext._device_policy(policy)
        fp = ext.fastpath
        row, op = compiled.scheduleonmetric_row, compiled.scheduleonmetric_op

        fp._rank.clear()
        assert fp.warm_rankings_batched(view, {(row, op)}) == 1
        key = (view.row_version(row), row, op)
        fused = fp._rank[key].copy()
        # already warm -> zero device work
        assert fp.warm_rankings_batched(view, {(row, op)}) == 0

        fp._rank.clear()
        per_pair = fp._ranking(view, row, op)
        np.testing.assert_array_equal(fused, per_pair)

        # end to end through the hook: a batch of verb requests warms the
        # cleared cache again (returns the fused-solve count)
        fp._rank.clear()
        bodies = make_bodies(names, "nodenames", count=3)
        requests = [
            HTTPRequest(
                method="POST",
                path="/scheduler/prioritize",
                headers={"Content-Type": "application/json"},
                body=b,
            )
            for b in bodies
        ]
        assert ext.warm_batch("/scheduler/prioritize", requests) == 1
        assert key in fp._rank

    def test_filter_warm_counts_device_work(self):
        """A Filter batch warms each distinct policy's violation set once
        and reports the computation; a warm repeat reports zero."""
        ext, names = build_extender(48, device=True)
        policy = ext.cache.read_policy("default", "load-pol")
        compiled, view = ext._device_policy(policy)
        fp = ext.fastpath

        fp._violations.clear()
        requests = [
            HTTPRequest(
                method="POST",
                path="/scheduler/filter",
                headers={"Content-Type": "application/json"},
                body=b,
            )
            for b in make_bodies(names, "nodenames", count=3)
        ]
        assert ext.warm_batch("/scheduler/filter", requests) == 1
        assert ext.warm_batch("/scheduler/filter", requests) == 0
        # the warmed set is the one the verb path serves from (identity)
        assert fp.warm_violations(compiled, view) == 0
        assert fp.violation_set(compiled, view) is not None


class _BlockingScheduler:
    """Scheduler whose verbs block until released (backpressure tests)."""

    def __init__(self):
        self.release = threading.Event()

    def _wait(self, request):
        self.release.wait(15)
        return HTTPResponse.json(b"[]\n")

    prioritize = _wait
    filter = _wait

    def bind(self, request):
        return HTTPResponse(status=404)


class TestBackpressure:
    def test_dispatcher_sheds_past_queue_depth_and_recovers(self):
        """Unit-level: saturation -> immediate 503 + Retry-After; drain ->
        admission recovers."""

        release = threading.Event()

        def slow_route(request):
            release.wait(15)
            return HTTPResponse(status=200)

        async def scenario():
            dispatcher = MicroBatchDispatcher(
                route=slow_route,
                window_s=0.0,
                max_batch=1,
                max_queue_depth=2,
                retry_after_s=7,
            )
            loop = asyncio.get_running_loop()
            dispatcher.start(loop)
            try:
                requests = [
                    HTTPRequest("POST", "/x", {}, b"") for _ in range(6)
                ]
                futures = [dispatcher.submit(r) for r in requests]
                # give the batcher a beat to pull the first request into
                # the (blocked) solve, then release everything
                await asyncio.sleep(0.1)
                release.set()
                responses = await asyncio.gather(*futures)
                rejected = [r for r in responses if r.status == 503]
                served = [r for r in responses if r.status == 200]
                assert rejected, "saturation must shed load"
                assert served, "admitted requests must still be served"
                for r in rejected:
                    assert r.headers.get("Retry-After") == "7"
                # drained queue -> a fresh request is admitted and served
                again = await dispatcher.submit(
                    HTTPRequest("POST", "/x", {}, b"")
                )
                assert again.status == 200
            finally:
                await dispatcher.stop()

        asyncio.run(scenario())

    def test_backpressure_over_the_wire(self):
        """Socket-level: a saturated async service answers 503 with
        Retry-After, then recovers once the queue drains."""
        scheduler = _BlockingScheduler()
        server = AsyncServer(
            scheduler, window_s=0.0, max_batch=1, max_queue_depth=1
        )
        server.start_server(
            port="0", unsafe=True, host="127.0.0.1", block=False
        )
        assert server.wait_ready(10)
        try:
            n = 5
            statuses = [None] * n
            headers = [None] * n

            def client(i):
                statuses[i], headers[i], _ = _raw_request(
                    server.port, _post("/scheduler/prioritize", b"{}")
                )

            threads = [
                threading.Thread(target=client, args=(i,)) for i in range(n)
            ]
            for t in threads:
                t.start()
                time.sleep(0.05)  # first fills the solve, next the queue
            time.sleep(0.2)
            scheduler.release.set()
            for t in threads:
                t.join(15)
            assert 503 in statuses
            assert 200 in statuses
            for status, hdrs in zip(statuses, headers):
                if status == 503:
                    assert "retry-after" in hdrs
            # recovery: queue drained, a fresh request is served
            status, _, _ = _raw_request(
                server.port, _post("/scheduler/prioritize", b"{}")
            )
            assert status == 200
        finally:
            server.shutdown()


class TestConcurrencyScaling:
    def test_c8_p99_within_3x_c1(self):
        """The acceptance bar (ISSUE 1): on the async path, c=8 p99 stays
        within 3x c=1 (threaded was 8-12x, round-5 verdict) and
        requests/s INCREASES with concurrency.  Hermetic socket
        measurement, best-of-3 per concurrency to shed scheduler noise."""
        ext, names = build_extender(256, device=True)
        server = _start_async(ext)
        try:
            bodies = make_bodies(names, "nodenames")
            drive(server.port, bodies[:5], 24, concurrency=1)  # warm
            best = {}
            for conc, requests in ((1, 120), (8, 240)):
                runs = [
                    drive(server.port, bodies, requests, concurrency=conc)
                    for _ in range(3)
                ]
                best[conc] = min(runs, key=lambda r: r["p99_ms"])
            assert best[8]["p99_ms"] <= 3.0 * best[1]["p99_ms"], best
            assert (
                best[8]["requests_per_s"] > best[1]["requests_per_s"]
            ), best
        finally:
            server.shutdown()

"""Wire-layer tests: middleware parity, routing, and JSON round-trips
(modeled on the reference's httptest-driven handler tests,
telemetry-aware-scheduling/pkg/telemetryscheduler/scheduler_test.go)."""

import http.client
import json
import threading

import pytest

from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
    Server,
    apply_middleware,
)
from platform_aware_scheduling_tpu.extender.types import (
    Args,
    BindingArgs,
    BindingResult,
    DecodeError,
    FilterResult,
    HostPriority,
    decode_host_priority_list,
    encode_host_priority_list,
)
from platform_aware_scheduling_tpu.kube.objects import Node, Pod


class EchoScheduler:
    """Records calls; returns canned bodies."""

    def __init__(self):
        self.calls = []

    def filter(self, request):
        self.calls.append(("filter", request.body))
        return HTTPResponse.json(b'{"Error": ""}')

    def prioritize(self, request):
        self.calls.append(("prioritize", request.body))
        return HTTPResponse.json(b"[]")

    def bind(self, request):
        self.calls.append(("bind", request.body))
        return HTTPResponse.json(b'{"Error": ""}')


def make_request(method="POST", path="/scheduler/filter", content_type="application/json", body=b"{}"):
    headers = {}
    if content_type is not None:
        headers["Content-Type"] = content_type
    return HTTPRequest(method=method, path=path, headers=headers, body=body)


class TestMiddleware:
    """Status-code parity with extender/scheduler.go:15-52."""

    def handler(self, request):
        return HTTPResponse(status=200, body=b"ok")

    def test_wrong_content_type_404(self):
        resp = apply_middleware(self.handler, make_request(content_type="text/plain"))
        assert resp.status == 404

    def test_content_type_with_charset_rejected(self):
        # exact string comparison, as in the reference
        resp = apply_middleware(
            self.handler, make_request(content_type="application/json; charset=utf-8")
        )
        assert resp.status == 404

    def test_missing_content_type_404(self):
        resp = apply_middleware(self.handler, make_request(content_type=None))
        assert resp.status == 404

    def test_oversized_body_500(self):
        req = make_request()
        req.body = b"x"  # fake the size via a slotted override of len check
        big = HTTPRequest(req.method, req.path, req.headers, b"0" * 10)
        big.body = b"0" * 10
        # build a request whose body exceeds 1 GB without allocating one:
        class FakeBody(bytes):
            def __len__(self):
                return 2 * 1000 * 1000 * 1000

        big.body = FakeBody()
        resp = apply_middleware(self.handler, big)
        assert resp.status == 500

    def test_non_post_405(self):
        resp = apply_middleware(self.handler, make_request(method="GET"))
        assert resp.status == 405

    def test_ok_passthrough(self):
        resp = apply_middleware(self.handler, make_request())
        assert resp.status == 200 and resp.body == b"ok"


class TestRouting:
    def test_known_routes_dispatch(self):
        scheduler = EchoScheduler()
        server = Server(scheduler)
        for verb in ("filter", "prioritize", "bind"):
            resp = server.route(make_request(path=f"/scheduler/{verb}"))
            assert resp.status == 200
        assert [c[0] for c in scheduler.calls] == ["filter", "prioritize", "bind"]

    def test_unknown_path_404_with_json_header(self):
        server = Server(EchoScheduler())
        resp = server.route(make_request(path="/nope"))
        assert resp.status == 404
        assert resp.headers.get("Content-Type") == "application/json"


class TestWireTypes:
    def test_args_roundtrip(self):
        pod = Pod({"metadata": {"name": "p1", "namespace": "default",
                                "labels": {"telemetry-policy": "pol"}}})
        nodes = [Node({"metadata": {"name": "node1"}}),
                 Node({"metadata": {"name": "node2"}})]
        args = Args(pod=pod, nodes=nodes, node_names=None)
        decoded = Args.from_json(args.to_json())
        assert decoded.pod.name == "p1"
        assert decoded.pod.get_labels()["telemetry-policy"] == "pol"
        assert [n.name for n in decoded.nodes] == ["node1", "node2"]
        assert decoded.node_names is None

    def test_args_node_names_mode(self):
        args = Args.from_json(json.dumps(
            {"Pod": {"metadata": {"name": "p"}}, "Nodes": None,
             "NodeNames": ["a", "b"]}).encode())
        assert args.nodes is None
        assert args.node_names == ["a", "b"]

    def test_host_priority_list_roundtrip(self):
        hps = [HostPriority("node1", 10), HostPriority("node2", 9)]
        body = encode_host_priority_list(hps)
        obj = json.loads(body)
        assert obj == [{"Host": "node1", "Score": 10}, {"Host": "node2", "Score": 9}]
        assert decode_host_priority_list(body) == hps

    def test_filter_result_shape(self):
        result = FilterResult(
            nodes=[Node({"metadata": {"name": "n1"}})],
            node_names=["n1", ""],
            failed_nodes={"n2": "Node violates"},
            error="",
        )
        obj = json.loads(result.to_json())
        assert obj["Nodes"]["items"][0]["metadata"]["name"] == "n1"
        assert obj["NodeNames"] == ["n1", ""]
        assert obj["FailedNodes"] == {"n2": "Node violates"}
        assert obj["Error"] == ""

    def test_binding_args_decode(self):
        args = BindingArgs.from_json(json.dumps(
            {"PodName": "p", "PodNamespace": "ns", "PodUID": "u1", "Node": "n1"}
        ).encode())
        assert (args.pod_name, args.pod_namespace, args.pod_uid, args.node) == (
            "p", "ns", "u1", "n1")

    def test_binding_args_type_mismatch_is_decode_error(self):
        """Go decode parity: non-string Bind fields fail the whole decode
        (null into a value-typed string field has no effect and keeps the
        zero value)."""
        for body in (
            b'{"PodName": 3, "Node": "n"}',
            b'{"podUID": ["u"], "Node": "n"}',
            b'{"Node": {"name": "n"}}',
        ):
            with pytest.raises(DecodeError):
                BindingArgs.from_json(body)
        args = BindingArgs.from_json(b'{"PodName": null, "Node": "n"}')
        assert (args.pod_name, args.node) == ("", "n")

    def test_binding_result(self):
        assert json.loads(BindingResult().to_json()) == {"Error": ""}
        assert BindingResult.from_json(b'{"Error": "boom"}').error == "boom"


class TestLiveServer:
    """End-to-end over a real socket (unsafe/plain-HTTP mode)."""

    @pytest.fixture()
    def server(self):
        scheduler = EchoScheduler()
        server = Server(scheduler)
        server.start_server(port="0", unsafe=True, host="127.0.0.1", block=False)
        assert server.wait_ready()
        yield server, scheduler
        server.shutdown()

    def post(self, port, path, body=b"{}", content_type="application/json"):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
        headers = {"Content-Type": content_type} if content_type else {}
        conn.request("POST", path, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        conn.close()
        return resp.status, data

    def test_post_filter(self, server):
        srv, scheduler = server
        status, data = self.post(srv.port, "/scheduler/filter")
        assert status == 200
        assert json.loads(data) == {"Error": ""}
        assert scheduler.calls[0][0] == "filter"

    def test_unknown_path(self, server):
        srv, _ = server
        status, _ = self.post(srv.port, "/bogus")
        assert status == 404

    def test_wrong_content_type(self, server):
        srv, _ = server
        status, _ = self.post(srv.port, "/scheduler/filter", content_type="text/plain")
        assert status == 404

    def test_get_rejected(self, server):
        srv, _ = server
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=5)
        conn.request("GET", "/scheduler/filter", headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        conn.close()
        assert resp.status == 405

    def test_concurrent_posts(self, server):
        srv, scheduler = server
        errors = []

        def worker():
            try:
                status, _ = self.post(srv.port, "/scheduler/prioritize")
                assert status == 200
            except Exception as exc:  # pragma: no cover
                errors.append(exc)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(scheduler.calls) == 8


class TestDuration:
    def test_parse(self):
        from platform_aware_scheduling_tpu.utils.duration import parse_duration

        assert parse_duration("5s") == 5.0
        assert parse_duration("2s") == 2.0
        assert parse_duration("100ms") == 0.1
        assert parse_duration("1.5h") == 5400.0
        assert parse_duration("1m30s") == 90.0
        with pytest.raises(ValueError):
            parse_duration("5")
        with pytest.raises(ValueError):
            parse_duration("")

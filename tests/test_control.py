"""Budget feedback control gates (ISSUE 15, docs/observability.md
"Budget feedback control").

The contracts pinned here, not merely promised in docstrings:

  * bounded actuation — every knob clamps to its declared ladder ends,
    moves at most one ladder step per engine tick, and validates its
    ladder at attach time;
  * hysteresis — tightening is immediate on a page or a burned budget,
    loosening waits for a consecutive-healthy-tick hold, and the band
    between the thresholds resets the recovery streak (no flapping);
  * trend pre-arm — a predicted storm tightens the shed knob one step
    from baseline BEFORE any budget burns, and never fights the
    ordinary hysteresis once armed;
  * fail-fast wiring — --sloControl=on without --slo=on dies at flag
    parse (exit 2) on both front-ends; the default (off) constructs
    nothing, emits no pas_control_* family, and leaves every verb
    response byte-identical on the wire;
  * full observability — GET /debug/control serves 404/405/200 on both
    front-ends, actuations land on pas_control_* and in the decision
    log with provenance;
  * the closed loop beats the static config — the twin head-to-head
    programs (metric storm + retry storm; deployment wave + eviction
    outage) end with strictly more error budget under self-tuning, and
    a quiet diurnal day with the controller armed ends with ZERO
    actuations.
"""

import json

import pytest

from benchmarks.http_load import build_extender, make_bodies
from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.forecast.engine import Forecaster
from platform_aware_scheduling_tpu.rebalance.loop import Rebalancer
from platform_aware_scheduling_tpu.tas.degraded import (
    DEFAULT_LKG_BOUND_MULTIPLE,
    DegradedModeController,
)
from platform_aware_scheduling_tpu.utils.control import (
    DIRECTION_LOOSEN,
    DIRECTION_TIGHTEN,
    TRIGGER_TREND,
    BudgetController,
    Knob,
)
from platform_aware_scheduling_tpu.utils.decisions import DecisionLog
from platform_aware_scheduling_tpu.utils.slo import SLOEngine, default_slos
from wirehelpers import get_request, post_bytes, raw_request, start_async, \
    start_threaded


class FakeQueue:
    """The admission-knob target shape: a live-read depth field."""

    def __init__(self, depth=64):
        self.max_queue_depth = depth


class FakeCache:
    """Just enough cache surface for a Forecaster to assemble."""

    def __init__(self):
        self.on_refresh_pass = []
        self.on_metric_delete = []

    def configure_history(self, window):
        pass


def make_forecaster(window=8):
    return Forecaster(FakeCache(), None, window=window, use_device=False)


def controller_with_admission(depth=64, floor=4, **kwargs):
    ctl = BudgetController(None, decision_log=DecisionLog(), **kwargs)
    queue = FakeQueue(depth)
    knob = ctl.attach_admission(queue, floor=floor)
    return ctl, queue, knob


def burn(slo="verb_availability", budget=0.0, alert="page"):
    return {slo: {"error_budget_remaining": budget, "alert": alert}}


def healthy(slo="verb_availability", budget=1.0):
    return {slo: {"error_budget_remaining": budget, "alert": "ok"}}


# ---------------------------------------------------------------------------
# knob mechanics: ladders, clamps, rate limit
# ---------------------------------------------------------------------------


class TestKnobMechanics:
    def test_ladder_validation(self):
        with pytest.raises(ValueError, match=">= 2"):
            Knob("k", "s", [4], lambda v: None)
        with pytest.raises(ValueError, match="monotonic"):
            Knob("k", "s", [4, 2, 3], lambda v: None)
        with pytest.raises(ValueError, match="monotonic"):
            Knob("k", "s", [4, 4], lambda v: None)

    def test_one_step_per_tick_and_clamp(self):
        writes = []
        knob = Knob("k", "s", [64, 32, 16], writes.append)
        assert knob.step(DIRECTION_TIGHTEN, tick=1)
        # second step in the SAME tick is refused — the rate limit
        assert not knob.step(DIRECTION_TIGHTEN, tick=1)
        assert knob.step(DIRECTION_TIGHTEN, tick=2)
        # clamped at the tight end
        assert not knob.step(DIRECTION_TIGHTEN, tick=3)
        assert knob.setting == 16
        assert writes == [32, 16]
        # and back: clamped at baseline
        assert knob.step(DIRECTION_LOOSEN, tick=4)
        assert knob.step(DIRECTION_LOOSEN, tick=5)
        assert not knob.step(DIRECTION_LOOSEN, tick=6)
        assert knob.setting == 64

    def test_controller_clamps_every_attached_knob(self):
        """Drive far more burn ticks than any ladder has rungs: every
        knob must pin at its declared [min, max] ends, never past."""
        ctl = BudgetController(None, decision_log=DecisionLog())
        queue = FakeQueue(64)
        ctl.attach_admission(queue, floor=4)
        rebalancer = Rebalancer(None, None, hysteresis_cycles=3)
        baseline_moves = rebalancer.replanner.max_moves
        ctl.attach_rebalancer(rebalancer)
        forecaster = make_forecaster()
        ctl.attach_forecaster(forecaster)
        degraded = DegradedModeController(None)
        ctl.attach_degraded(degraded)
        evaluations = {}
        evaluations.update(burn("verb_availability"))
        evaluations.update(burn("eviction_safety"))
        evaluations.update(burn("telemetry_freshness"))
        for _ in range(20):
            ctl.on_tick(evaluations)
        snap = ctl.snapshot()
        assert len(snap["knobs"]) == 6
        for row in snap["knobs"]:
            assert row["level"] == row["levels"] - 1  # pinned tight
            assert row["min"] <= row["setting"] <= row["max"]
        # the live components took the tight settings
        assert queue.max_queue_depth == 4
        assert rebalancer.replanner.max_moves == 1
        assert rebalancer.drift.k == 8  # 3 -> 4 -> 5 -> 2*3+2
        assert forecaster.horizon_cap == 2
        assert degraded.lkg_bound_multiple == 1.0
        # and loosening all the way home restores every baseline
        for _ in range(200):
            ctl.on_tick({
                name: {"error_budget_remaining": 1.0, "alert": "ok"}
                for name in ("verb_availability", "eviction_safety",
                             "telemetry_freshness")
            })
        assert queue.max_queue_depth == 64
        assert rebalancer.replanner.max_moves == baseline_moves
        assert rebalancer.drift.k == 3
        assert degraded.lkg_bound_multiple == DEFAULT_LKG_BOUND_MULTIPLE

    def test_rate_limit_one_ladder_step_per_engine_tick(self):
        ctl, queue, knob = controller_with_admission(64, floor=4)
        ctl.on_tick(burn())
        assert queue.max_queue_depth == 32  # exactly ONE step
        ctl.on_tick(burn())
        assert queue.max_queue_depth == 16

    def test_duplicate_knob_rejected(self):
        ctl, _queue, _knob = controller_with_admission()
        with pytest.raises(ValueError, match="duplicate"):
            ctl.attach_admission(FakeQueue(32))


# ---------------------------------------------------------------------------
# the control policy: hysteresis, pre-arm
# ---------------------------------------------------------------------------


class TestHysteresis:
    def test_tighten_on_page_or_burned_budget(self):
        ctl, queue, _ = controller_with_admission()
        ctl.on_tick(burn(budget=0.9, alert="page"))  # page alone
        assert queue.max_queue_depth == 32
        ctl.on_tick(burn(budget=0.1, alert="ok"))  # budget alone
        assert queue.max_queue_depth == 16

    def test_loosen_waits_for_the_hold(self):
        ctl, queue, _ = controller_with_admission()
        ctl.on_tick(burn())
        ctl.on_tick(burn())
        assert queue.max_queue_depth == 16
        # two healthy ticks: still held (loosen_hold_ticks = 3)
        ctl.on_tick(healthy())
        ctl.on_tick(healthy())
        assert queue.max_queue_depth == 16
        ctl.on_tick(healthy())
        assert queue.max_queue_depth == 32
        # the streak restarts after each loosen step
        ctl.on_tick(healthy())
        ctl.on_tick(healthy())
        assert queue.max_queue_depth == 32

    def test_hysteresis_band_resets_the_streak(self):
        ctl, queue, _ = controller_with_admission()
        ctl.on_tick(burn())
        assert queue.max_queue_depth == 32
        # budget between tighten (0.25) and loosen (0.50): hold position
        ctl.on_tick(healthy(budget=0.4))
        ctl.on_tick(healthy(budget=0.4))
        ctl.on_tick(healthy(budget=0.4))
        ctl.on_tick(healthy(budget=0.4))
        assert queue.max_queue_depth == 32  # never loosened
        # and a dip into the band RESETS a partial recovery streak
        ctl.on_tick(healthy())
        ctl.on_tick(healthy())
        ctl.on_tick(healthy(budget=0.4))  # streak broken
        ctl.on_tick(healthy())
        ctl.on_tick(healthy())
        assert queue.max_queue_depth == 32
        ctl.on_tick(healthy())  # third consecutive healthy tick
        assert queue.max_queue_depth == 64

    def test_threshold_order_validated(self):
        with pytest.raises(ValueError, match="hysteresis"):
            BudgetController(
                None, tighten_budget=0.5, loosen_budget=0.25,
                decision_log=DecisionLog(),
            )


class TestTrendPrearm:
    def test_predicted_storm_tightens_one_step_from_baseline(self):
        signal = {"storm": False}
        ctl, queue, knob = controller_with_admission(
            trend_source=lambda: (signal["storm"], "test trend"),
        )
        ctl.on_tick(healthy())
        assert queue.max_queue_depth == 64  # no storm, no pre-arm
        signal["storm"] = True
        # budget in the hysteresis band: no burn, no recovery streak —
        # the pre-arm signal is the ONLY thing moving the knob
        ctl.on_tick(healthy(budget=0.4))
        assert queue.max_queue_depth == 32  # pre-armed ONE step
        ctl.on_tick(healthy(budget=0.4))
        assert queue.max_queue_depth == 32  # never deeper than one
        snap = ctl.snapshot()
        assert snap["prearmed"] is True
        assert snap["recent"][-1]["trigger"] == TRIGGER_TREND
        # the gauge is visible
        assert "pas_control_prearmed" in ctl.counters.prometheus_text()
        # the storm never materializes: the ordinary hysteresis owns
        # the knob and stands the pre-arm down after the healthy hold
        for _ in range(ctl.loosen_hold_ticks):
            ctl.on_tick(healthy())
        assert queue.max_queue_depth == 64

    def test_prearm_never_fights_real_burn(self):
        ctl, queue, _ = controller_with_admission(
            trend_source=lambda: (True, "always stormy"),
        )
        ctl.on_tick(burn())
        # burn already tightened this tick; the pre-arm pass must not
        # take a second step through the same knob
        assert queue.max_queue_depth == 32

    def test_trend_source_crash_is_contained(self):
        def boom():
            raise RuntimeError("trend source broke")

        ctl, queue, _ = controller_with_admission(trend_source=boom)
        ctl.on_tick(healthy())
        assert queue.max_queue_depth == 64
        assert ctl.snapshot()["prearmed"] is False


# ---------------------------------------------------------------------------
# actuator-side validation (the components defend themselves too)
# ---------------------------------------------------------------------------


class TestActuatorValidation:
    def test_rebalancer_set_aggressiveness(self):
        rebalancer = Rebalancer(None, None, hysteresis_cycles=3)
        with pytest.raises(ValueError, match="max_moves"):
            rebalancer.set_aggressiveness(max_moves=0)
        with pytest.raises(ValueError, match="hysteresis_k"):
            rebalancer.set_aggressiveness(hysteresis_k=0)
        rebalancer.set_aggressiveness(max_moves=2, hysteresis_k=5)
        assert rebalancer.replanner.max_moves == 2
        assert rebalancer.drift.k == 5

    def test_forecaster_set_extrapolation_bounds(self):
        forecaster = make_forecaster()
        with pytest.raises(ValueError, match="band_bound"):
            forecaster.set_extrapolation_bounds(band_bound=0.0)
        with pytest.raises(ValueError, match="horizon_cap"):
            forecaster.set_extrapolation_bounds(horizon_cap=0)
        forecaster.set_extrapolation_bounds(band_bound=0.1, horizon_cap=3)
        assert forecaster.band_bound == 0.1
        assert forecaster.horizon_cap == 3
        assert forecaster.snapshot()["horizon_cap"] == 3

    def test_degraded_status_reports_the_multiple(self):
        degraded = DegradedModeController(None)
        assert degraded.status()["lkg_bound_multiple"] == \
            DEFAULT_LKG_BOUND_MULTIPLE
        degraded.lkg_bound_multiple = 1.5
        assert degraded.status()["lkg_bound_multiple"] == 1.5


# ---------------------------------------------------------------------------
# wiring: flags, engine subscription, decision provenance
# ---------------------------------------------------------------------------


class TestFlagWiring:
    @pytest.mark.parametrize("front_end", ["tas", "gas"])
    def test_control_without_slo_fails_fast(self, front_end):
        from platform_aware_scheduling_tpu.cmd import common, gas, tas

        mod = tas if front_end == "tas" else gas
        parser = mod.build_arg_parser()
        args = parser.parse_args(["--sloControl", "on"])  # --slo left off
        with pytest.raises(SystemExit) as exc:
            common.validate_control_flags(parser, args)
        assert exc.value.code == 2  # a flag error, not a crash

    def test_default_off_builds_nothing(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        parser = tas.build_arg_parser()
        args = parser.parse_args([])
        assert args.sloControl == "off"
        common.validate_control_flags(parser, args)  # off + off: fine
        ext, _names = build_extender(8, device=True)
        assert common.build_budget_controller(args, ext, None) is None
        assert ext.control is None
        assert "pas_control_" not in ext.metrics_text()

    def test_flag_on_attaches_available_actuators(self):
        from platform_aware_scheduling_tpu.cmd import common, tas

        parser = tas.build_arg_parser()
        args = parser.parse_args(["--slo", "on", "--sloControl", "on"])
        common.validate_control_flags(parser, args)
        ext, _names = build_extender(8, device=True)
        engine = common.build_slo_engine(args, ext, cache=ext.cache)
        controller = common.build_budget_controller(args, ext, engine)
        assert controller is not None
        assert ext.control is controller
        # the bare bench extender has no rebalancer/forecaster/degraded
        # wired, and the admission knob is attached post-build_server —
        # so the controller may start knobless; what must hold is that
        # the engine drives it
        before = controller.snapshot()["ticks"]
        engine.tick()
        assert controller.snapshot()["ticks"] == before + 1
        assert "pas_control_ticks_total" in ext.metrics_text()

    def test_engine_subscription_survives_controller_crash(self):
        """on_tick never raises into the engine: a controller bug must
        not take the judge down."""
        engine = SLOEngine(default_slos())
        controller = BudgetController(engine, decision_log=DecisionLog())

        def explode(value):
            raise RuntimeError("actuator broke")

        controller.add_knob(
            Knob("bomb", "verb_availability", [2, 1], explode)
        )
        controller.on_tick(burn())  # swallowed, logged
        engine.tick()  # and the engine's own tick path stays healthy

    def test_actuations_carry_decision_provenance(self):
        log = DecisionLog()
        ctl = BudgetController(None, decision_log=log)
        queue = FakeQueue(64)
        ctl.attach_admission(queue, floor=4)
        ctl.on_tick(burn())
        snap = ctl.snapshot()
        assert snap["recent"], "actuation must land in the recent ring"
        record = snap["recent"][-1]
        assert record["knob"] == "admission_queue_depth"
        assert record["direction"] == DIRECTION_TIGHTEN
        assert record["trigger"] == "verb_availability"
        assert record["from"] == 64 and record["to"] == 32
        assert "budget" in record["reason"]
        rendered = ctl.counters.prometheus_text()
        assert 'pas_control_actuations_total{' in rendered
        assert 'direction="tighten"' in rendered
        assert 'pas_control_knob_setting{knob="admission_queue_depth"}' \
            in rendered


# ---------------------------------------------------------------------------
# the wire: /debug/control, /metrics, off-path byte identity
# ---------------------------------------------------------------------------


class TestDebugControlEndpoint:
    @pytest.mark.parametrize("serving", ["threaded", "async"])
    def test_codes_and_payload(self, serving):
        ext, _names = build_extender(8, device=True)
        server = (
            start_async(ext) if serving == "async" else start_threaded(ext)
        )
        try:
            # 404 while unwired (--sloControl=off)
            status, _h, body = get_request(server.port, "/debug/control")
            assert status == 404
            assert b"error" in body
            # 405 on non-GET
            controller = BudgetController(None, decision_log=DecisionLog())
            controller.attach_admission(FakeQueue(64), floor=4)
            ext.control = controller
            status, _h, _b = raw_request(
                server.port, post_bytes("/debug/control", b"{}")
            )
            assert status == 405
            # 200 with the knob/provenance payload once wired
            controller.on_tick(burn())
            status, _h, body = get_request(server.port, "/debug/control")
            assert status == 200
            snap = json.loads(body)
            assert snap["enabled"] is True
            assert snap["thresholds"]["tighten_budget"] == 0.25
            names = {row["name"] for row in snap["knobs"]}
            assert "admission_queue_depth" in names
            assert snap["recent"][-1]["direction"] == "tighten"
            # /metrics grows the family only while wired
            status, _h, metrics = get_request(server.port, "/metrics")
            assert status == 200
            assert b"pas_control_knob_setting" in metrics
            ext.control = None
            status, _h, metrics = get_request(server.port, "/metrics")
            assert b"pas_control_" not in metrics
        finally:
            server.shutdown()


class TestOffPathPins:
    def test_controller_never_touches_a_verb_response(self):
        """ISSUE 15 acceptance: a wired (but not actuating) controller
        changes no verb response byte — it only ever mutates knobs
        other components already read live."""
        ext_off, names = build_extender(8, device=True)
        ext_on, _names2 = build_extender(8, device=True)
        controller = BudgetController(None, decision_log=DecisionLog())
        controller.attach_admission(FakeQueue(64), floor=4)
        ext_on.control = controller
        body = make_bodies(names, "nodenames", count=1)[0]
        for verb in ("prioritize", "filter"):
            request = HTTPRequest(
                method="POST",
                path=f"/scheduler/{verb}",
                headers={"Content-Type": "application/json"},
                body=body,
            )
            off = getattr(ext_off, verb)(request)
            on = getattr(ext_on, verb)(request)
            assert off.status == on.status
            assert off.body == on.body


# ---------------------------------------------------------------------------
# the closed loop beats the static config (twin head-to-heads)
# ---------------------------------------------------------------------------


class TestHeadToHead:
    def test_self_tuning_strictly_beats_static_and_quiet_day_is_quiet(self):
        """The PR's headline acceptance, in-process: both head-to-head
        programs end with strictly more error budget under self-tuning,
        and the armed controller does NOTHING on a healthy diurnal
        day."""
        from platform_aware_scheduling_tpu.testing.twin import (
            control_headtohead,
        )

        out = control_headtohead()
        for key, entry in out["scenarios"].items():
            assert entry["static"]["actuations"] == 0, key
            assert entry["self_tuning"]["actuations"] > 0, key
            assert entry["strictly_better"], (
                f"{key}: static {entry['static']['budget']} vs "
                f"self-tuning {entry['self_tuning']['budget']}"
            )
        assert out["all_strictly_better"]
        assert out["diurnal_quiet"]["ok"], out["diurnal_quiet"]

"""Device-kernel correctness: ops/{i64,rules,scoring} cross-checked against
the exact host implementations (tas/strategies/core.py) on adversarial
int64 values — full range, ties, negatives, sentinels."""

import numpy as np
import jax.numpy as jnp
import pytest

from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.rules import (
    OP_EQUALS,
    OP_GREATER_THAN,
    OP_LESS_THAN,
    RuleSet,
    evaluate_rules,
    rule_matches,
    violated_nodes,
)
from platform_aware_scheduling_tpu.ops.scoring import (
    filter_kernel,
    ordinal_scores,
    prioritize_kernel,
)

EDGE = np.array(
    [
        -(2**63),
        -(2**63) + 1,
        -(2**32) - 1,
        -(2**32),
        -(2**32) + 1,
        -1,
        0,
        1,
        2**31 - 1,
        2**31,
        2**32 - 1,
        2**32,
        2**32 + 1,
        2**63 - 2,
        2**63 - 1,
    ],
    dtype=np.int64,
)


def rand_i64(rng, n):
    exp = rng.integers(0, 63, size=n)
    base = rng.integers(0, 2**62, size=n, dtype=np.int64) >> exp.astype(np.int64)
    sign = rng.choice([-1, 1], size=n).astype(np.int64)
    return base * sign


class TestI64:
    def test_roundtrip(self):
        vals = np.concatenate([EDGE, rand_i64(np.random.default_rng(0), 100)])
        split = i64.from_int64(vals)
        np.testing.assert_array_equal(i64.to_int64_np(split), vals)

    def test_cmp_matches_python(self):
        rng = np.random.default_rng(1)
        a = np.concatenate([EDGE, rand_i64(rng, 200), EDGE])
        b = np.concatenate([rand_i64(rng, len(EDGE)), rand_i64(rng, 200), EDGE])
        got = np.asarray(i64.cmp(i64.from_int64(a), i64.from_int64(b)))
        want = np.sign(a.astype(object) - b.astype(object)).astype(np.int32)
        np.testing.assert_array_equal(got, want)

    def test_flip_reverses_order(self):
        vals = np.sort(np.concatenate([EDGE, rand_i64(np.random.default_rng(2), 50)]))
        flipped = i64.to_int64_np(i64.flip(i64.from_int64(vals)))
        assert list(flipped) == sorted(flipped, reverse=True)

    def test_add_sub_neg(self):
        rng = np.random.default_rng(3)
        a = rand_i64(rng, 100) // 2
        b = rand_i64(rng, 100) // 2
        np.testing.assert_array_equal(
            i64.to_int64_np(i64.add(i64.from_int64(a), i64.from_int64(b))), a + b
        )
        np.testing.assert_array_equal(
            i64.to_int64_np(i64.sub(i64.from_int64(a), i64.from_int64(b))), a - b
        )
        np.testing.assert_array_equal(
            i64.to_int64_np(i64.neg(i64.from_int64(a))), -a
        )

    def test_sort_by_key_exact(self):
        rng = np.random.default_rng(4)
        vals = np.concatenate([EDGE, rand_i64(rng, 100)])
        idx = np.arange(len(vals), dtype=np.int32)
        (perm,) = i64.sort_by_key(i64.from_int64(vals), jnp.asarray(idx))
        got = vals[np.asarray(perm)]
        np.testing.assert_array_equal(got, np.sort(vals))


class TestRules:
    def test_rule_matches_all_ops(self):
        vals = EDGE
        targets = np.array([0] * len(EDGE), dtype=np.int64)
        v = i64.from_int64(vals)
        t = i64.from_int64(targets)
        lt_mask = np.asarray(rule_matches(v, jnp.int32(OP_LESS_THAN), t))
        gt_mask = np.asarray(rule_matches(v, jnp.int32(OP_GREATER_THAN), t))
        eq_mask = np.asarray(rule_matches(v, jnp.int32(OP_EQUALS), t))
        np.testing.assert_array_equal(lt_mask, vals < 0)
        np.testing.assert_array_equal(gt_mask, vals > 0)
        np.testing.assert_array_equal(eq_mask, vals == 0)

    def _ruleset(self, rows, ops, targets, active=None):
        r = len(rows)
        active = [True] * r if active is None else active
        t = i64.from_int64(np.asarray(targets, dtype=np.int64))
        return RuleSet(
            metric_row=jnp.asarray(np.asarray(rows, dtype=np.int32)),
            op_id=jnp.asarray(np.asarray(ops, dtype=np.int32)),
            target=t,
            active=jnp.asarray(np.asarray(active, dtype=bool)),
        )

    def test_violated_or_semantics(self):
        # 2 metrics x 4 nodes; rule0: m0 > 10, rule1: m1 < 5
        values = i64.from_int64(
            np.array([[20, 5, 20, 0], [9, 9, 1, 1]], dtype=np.int64)
        )
        present = jnp.asarray(
            np.array([[True, True, False, True], [True, True, True, False]])
        )
        rules = self._ruleset([0, 1], [OP_GREATER_THAN, OP_LESS_THAN], [10, 5])
        got = np.asarray(violated_nodes(values, present, rules))
        # node0: m0=20>10 -> violated; node1: m0=5, m1=9 -> no;
        # node2: m0 absent, m1=1<5 -> violated; node3: m0=0, m1 absent -> no
        np.testing.assert_array_equal(got, [True, False, True, False])

    def test_inactive_rules_ignored(self):
        values = i64.from_int64(np.array([[100, 100]], dtype=np.int64))
        present = jnp.asarray(np.ones((1, 2), dtype=bool))
        rules = self._ruleset([0, 0], [OP_GREATER_THAN, OP_GREATER_THAN], [0, 0],
                              active=[False, False])
        got = np.asarray(violated_nodes(values, present, rules))
        np.testing.assert_array_equal(got, [False, False])

    def test_evaluate_rules_shape(self):
        values = i64.from_int64(np.zeros((3, 5), dtype=np.int64))
        present = jnp.asarray(np.ones((3, 5), dtype=bool))
        rules = self._ruleset([0, 1, 2], [OP_EQUALS] * 3, [0, 0, 1])
        got = np.asarray(evaluate_rules(values, present, rules))
        assert got.shape == (3, 5)
        np.testing.assert_array_equal(got[2], [False] * 5)


def host_prioritize(values, valid, descending):
    """Reference semantics in pure python: stable sort of valid nodes by
    value (ties by index), score = 10 - rank."""
    idxs = [i for i in range(len(values)) if valid[i]]
    idxs.sort(key=lambda i: ((-values[i]) if descending else values[i], i))
    return {i: 10 - rank for rank, i in enumerate(idxs)}


class TestScoring:
    @pytest.mark.parametrize("op,descending", [(OP_LESS_THAN, False),
                                               (OP_GREATER_THAN, True)])
    def test_ordinal_scores_vs_host(self, op, descending):
        rng = np.random.default_rng(7)
        vals = np.concatenate([EDGE, rand_i64(rng, 40),
                               np.array([0, 0, 7, 7], dtype=np.int64)])
        valid = rng.random(len(vals)) > 0.3
        res = ordinal_scores(
            i64.from_int64(vals), jnp.asarray(valid), jnp.int32(op)
        )
        want = host_prioritize(list(vals), list(valid), descending)
        got_scores = np.asarray(res.scores)
        got_valid = np.asarray(res.valid)
        np.testing.assert_array_equal(got_valid, valid)
        for i, score in want.items():
            assert got_scores[i] == score, (i, vals[i])

    def test_ordinal_scores_input_order_for_equals(self):
        # non-LT/GT operator: no sort, score by input (index) order
        vals = np.array([5, 1, 9, 3], dtype=np.int64)
        valid = np.array([True, False, True, True])
        res = ordinal_scores(
            i64.from_int64(vals), jnp.asarray(valid), jnp.int32(OP_EQUALS)
        )
        scores = np.asarray(res.scores)
        assert scores[0] == 10 and scores[2] == 9 and scores[3] == 8

    def test_int64_min_greaterthan_sentinel_collision(self):
        # flip(INT64_MIN) == INT64_MAX == the invalid sentinel: valid lane
        # must still rank before invalid lanes
        vals = np.array([-(2**63), 4], dtype=np.int64)
        valid = np.array([True, False])
        res = ordinal_scores(
            i64.from_int64(vals), jnp.asarray(valid), jnp.int32(OP_GREATER_THAN)
        )
        assert np.asarray(res.scores)[0] == 10

    def test_prioritize_kernel_end_to_end(self):
        # metric matrix [2 metrics, 6 nodes]; rule: metric1 GreaterThan
        values = i64.from_int64(
            np.array(
                [[1, 2, 3, 4, 5, 6], [10, 60, 30, 0, 50, 40]], dtype=np.int64
            )
        )
        present = jnp.asarray(
            np.array(
                [[True] * 6, [True, True, True, False, True, True]]
            )
        )
        candidates = jnp.asarray(np.array([True, True, False, True, True, True]))
        res = prioritize_kernel(
            values, present, jnp.int32(1), jnp.int32(OP_GREATER_THAN), candidates
        )
        scores = np.asarray(res.scores)
        valid = np.asarray(res.valid)
        # valid candidates on metric1: n0=10, n1=60, n4=50, n5=40 (n2 not a
        # candidate, n3 absent) -> ranks: n1,n4,n5,n0
        np.testing.assert_array_equal(
            valid, [True, True, False, False, True, True]
        )
        assert scores[1] == 10 and scores[4] == 9 and scores[5] == 8 and scores[0] == 7

    def test_filter_kernel(self):
        values = i64.from_int64(np.array([[20, 5, 20, 0]], dtype=np.int64))
        present = jnp.asarray(np.array([[True, True, False, True]]))
        rules = RuleSet(
            metric_row=jnp.asarray(np.array([0], dtype=np.int32)),
            op_id=jnp.asarray(np.array([OP_GREATER_THAN], dtype=np.int32)),
            target=i64.from_int64(np.array([10], dtype=np.int64)),
            active=jnp.asarray(np.array([True])),
        )
        candidates = jnp.asarray(np.array([True, True, True, False]))
        got = np.asarray(filter_kernel(values, present, rules, candidates))
        # n0 violates (20>10); n1 ok; n2 absent from metric -> passes;
        # n3 not candidate
        np.testing.assert_array_equal(got, [False, True, True, False])

"""_wirec native wire path: byte parity with the pure-Python paths across
request shapes, and scanner strictness (fallback on any surprise)."""

import json

import numpy as np
import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils.quantity import Quantity

wirec = get_wirec()
pytestmark = pytest.mark.skipif(
    wirec is None, reason="no C toolchain for _wirec"
)


def build_extender(values=None, op="GreaterThan"):
    values = values or {"n1": 100, "n2": 50, "n3": 10, "n4": 70}
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default",
        "pol",
        TASPolicy.from_obj(
            make_policy("pol", strategies={"scheduleonmetric": [rule("m", op, 0)]})
        ),
    )
    cache.write_metric(
        "m", {n: NodeMetric(value=Quantity(str(v))) for n, v in values.items()}
    )
    return MetricsExtender(cache, mirror=mirror)


def request_from(body: bytes) -> HTTPRequest:
    return HTTPRequest(
        method="POST",
        path="/scheduler/prioritize",
        headers={"Content-Type": "application/json"},
        body=body,
    )


def args_body(names, labels=None, pod_extra=None, namespace="default") -> bytes:
    pod = {
        "metadata": {"name": "p", "namespace": namespace},
        "spec": {"containers": [{"name": "c", "resources": {}}]},
    }
    if labels is not None:
        pod["metadata"]["labels"] = labels
    if pod_extra:
        pod.update(pod_extra)
    return json.dumps(
        {
            "Pod": pod,
            "Nodes": {"items": [{"metadata": {"name": n}} for n in names]},
        }
    ).encode()


BODIES = [
    args_body(["n1", "n2", "n3", "n4"], labels={"telemetry-policy": "pol"}),
    args_body(["n3", "n1"], labels={"telemetry-policy": "pol"}),
    args_body(["n1", "ghost", "n4"], labels={"telemetry-policy": "pol"}),
    args_body(["n1"], labels=None),  # no labels at all -> 400 + []
    args_body(["n1"], labels={"other": "x"}),  # label absent -> 400 + []
    args_body(["n1"], labels={"telemetry-policy": "nope"}),  # unknown policy
    args_body([], labels={"telemetry-policy": "pol"}),  # empty items
    args_body(["n1", "n1", "n2"], labels={"telemetry-policy": "pol"}),  # dups
    args_body(["n2"], labels={"telemetry-policy": "pol"}, namespace="other"),
    # extra unknown fields everywhere; nested arrays/objects skipped
    args_body(
        ["n1", "n2"],
        labels={"telemetry-policy": "pol", "zz": "y"},
        pod_extra={"status": {"conditions": [{"a": [1, 2.5, -3e2, True, None]}]}},
    ),
    b'{"Pod": null, "Nodes": {"items": [{"metadata": {"name": "n1"}}]}}',
    b'{"Nodes": {"items": [{"metadata": {"name": "n1"}}]}}',
    b'{"Pod": {}, "Nodes": {"items": [{"spec": {}}]}}',  # node without name
    b'{"Pod": {}, "Nodes": null}',
    b'{"Pod": {}, "Nodes": {"items": null}}',
    b'{"Pod": {}}',
    b"",
    b"not json",
    b'[1, 2, 3]',
    b'{"Pod": {"metadata": {"labels": {"telemetry-policy": "pol"}}}, "NodeNames": ["n1"]}',
]


class TestParityWithPython:
    @pytest.mark.parametrize("body_idx", range(len(BODIES)))
    def test_native_equals_python(self, body_idx, monkeypatch):
        body = BODIES[body_idx]
        ext = build_extender()
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.status == python.status, body
        assert native.body == python.body, body

    def test_escaped_and_unicode_names(self, monkeypatch):
        names = ['we"ird\\name', "uniécode", "plain", "tab\tname"]
        values = {n: i + 1 for i, n in enumerate(names)}
        ext = build_extender(values=values)
        body = args_body(names, labels={"telemetry-policy": "pol"})
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.body == python.body
        assert json.loads(native.body)[0]["Host"] == "tab\tname"

    def test_parity_at_scale_with_random_subsets(self, monkeypatch):
        rng = np.random.default_rng(11)
        names = [f"node-{i:04d}" for i in range(500)]
        values = {n: int(rng.integers(0, 100)) for n in names}  # many ties
        ext = build_extender(values=values)
        for _ in range(5):
            subset = list(rng.choice(names, size=120, replace=False))
            body = args_body(subset, labels={"telemetry-policy": "pol"})
            native = ext.prioritize(request_from(body))
            monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
            python = ext.prioritize(request_from(body))
            monkeypatch.delenv("PAS_TPU_NO_NATIVE")
            assert native.body == python.body

    def test_planned_promotion_parity(self, monkeypatch):
        ext = build_extender()

        class StubPlanner:
            def planned_node(self, pod):
                return "n3"

        ext.planner = StubPlanner()
        body = args_body(["n1", "n2", "n3"], labels={"telemetry-policy": "pol"})
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert json.loads(native.body)[0]["Host"] == "n3"
        assert native.body == python.body


class TestScannerStrictness:
    @pytest.mark.parametrize(
        "bad",
        [
            b'{"Pod": {,}}',
            b'{"Pod": {}} trailing',
            b'{"Pod": {"metadata": {"labels": {"telemetry-policy": 5}}}, "Nodes": {"items": []}}',
            b'{"Nodes": {"items": [{}',
            b'{"Nodes": {"items": 7}}',
            b'{"a": 01}',
            b'{"a": truthy}',
            b'{"a": "\x01"}',
        ],
    )
    def test_surprises_raise(self, bad):
        with pytest.raises((ValueError, TypeError)):
            wirec.parse_prioritize(bad)

    def test_whitespace_tolerated(self):
        body = b' \n\t{ "Pod" : { "metadata" : { "name" : "p" } } , "Nodes" : { "items" : [ { "metadata" : { "name" : "n1" } } ] } } \n'
        parsed = wirec.parse_prioritize(body)
        assert parsed.pod_name == "p"
        assert parsed.node_names() == ["n1"]

    def test_last_duplicate_key_wins(self):
        body = (
            b'{"Nodes": {"items": [{"metadata": {"name": "a"}}]},'
            b' "Nodes": {"items": [{"metadata": {"name": "b"}}]}}'
        )
        parsed = wirec.parse_prioritize(body)
        assert parsed.node_names() == ["b"]

    def test_select_encode_empty_selection(self):
        parsed = wirec.parse_prioritize(
            b'{"Nodes": {"items": [{"metadata": {"name": "ghost"}}]}}'
        )
        table = wirec.build_table(["n1", "n2"])
        ranked = np.array([0, 1], dtype=np.int64)
        assert wirec.select_encode(parsed, table, ranked) == b"[]\n"

"""_wirec native wire path: byte parity with the pure-Python paths across
request shapes, and scanner strictness (fallback on any surprise)."""

import json

import numpy as np
import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils.quantity import Quantity

wirec = get_wirec()
pytestmark = pytest.mark.skipif(
    wirec is None, reason="no C toolchain for _wirec"
)


def build_extender(values=None, op="GreaterThan"):
    values = values or {"n1": 100, "n2": 50, "n3": 10, "n4": 70}
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default",
        "pol",
        TASPolicy.from_obj(
            make_policy("pol", strategies={"scheduleonmetric": [rule("m", op, 0)]})
        ),
    )
    cache.write_metric(
        "m", {n: NodeMetric(value=Quantity(str(v))) for n, v in values.items()}
    )
    return MetricsExtender(cache, mirror=mirror)


def request_from(body: bytes) -> HTTPRequest:
    return HTTPRequest(
        method="POST",
        path="/scheduler/prioritize",
        headers={"Content-Type": "application/json"},
        body=body,
    )


def args_body(names, labels=None, pod_extra=None, namespace="default") -> bytes:
    pod = {
        "metadata": {"name": "p", "namespace": namespace},
        "spec": {"containers": [{"name": "c", "resources": {}}]},
    }
    if labels is not None:
        pod["metadata"]["labels"] = labels
    if pod_extra:
        pod.update(pod_extra)
    return json.dumps(
        {
            "Pod": pod,
            "Nodes": {"items": [{"metadata": {"name": n}} for n in names]},
        }
    ).encode()


BODIES = [
    args_body(["n1", "n2", "n3", "n4"], labels={"telemetry-policy": "pol"}),
    args_body(["n3", "n1"], labels={"telemetry-policy": "pol"}),
    args_body(["n1", "ghost", "n4"], labels={"telemetry-policy": "pol"}),
    args_body(["n1"], labels=None),  # no labels at all -> 400 + []
    args_body(["n1"], labels={"other": "x"}),  # label absent -> 400 + []
    args_body(["n1"], labels={"telemetry-policy": "nope"}),  # unknown policy
    args_body([], labels={"telemetry-policy": "pol"}),  # empty items
    args_body(["n1", "n1", "n2"], labels={"telemetry-policy": "pol"}),  # dups
    args_body(["n2"], labels={"telemetry-policy": "pol"}, namespace="other"),
    # extra unknown fields everywhere; nested arrays/objects skipped
    args_body(
        ["n1", "n2"],
        labels={"telemetry-policy": "pol", "zz": "y"},
        pod_extra={"status": {"conditions": [{"a": [1, 2.5, -3e2, True, None]}]}},
    ),
    b'{"Pod": null, "Nodes": {"items": [{"metadata": {"name": "n1"}}]}}',
    b'{"Nodes": {"items": [{"metadata": {"name": "n1"}}]}}',
    b'{"Pod": {}, "Nodes": {"items": [{"spec": {}}]}}',  # node without name
    b'{"Pod": {}, "Nodes": null}',
    b'{"Pod": {}, "Nodes": {"items": null}}',
    b'{"Pod": {}}',
    b"",
    b"not json",
    b'[1, 2, 3]',
    b'{"Pod": {"metadata": {"labels": {"telemetry-policy": "pol"}}}, "NodeNames": ["n1"]}',
]


class TestParityWithPython:
    @pytest.mark.parametrize("body_idx", range(len(BODIES)))
    def test_native_equals_python(self, body_idx, monkeypatch):
        body = BODIES[body_idx]
        ext = build_extender()
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.status == python.status, body
        assert native.body == python.body, body

    def test_escaped_and_unicode_names(self, monkeypatch):
        names = ['we"ird\\name', "uniécode", "plain", "tab\tname"]
        values = {n: i + 1 for i, n in enumerate(names)}
        ext = build_extender(values=values)
        body = args_body(names, labels={"telemetry-policy": "pol"})
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.body == python.body
        assert json.loads(native.body)[0]["Host"] == "tab\tname"

    def test_parity_at_scale_with_random_subsets(self, monkeypatch):
        rng = np.random.default_rng(11)
        names = [f"node-{i:04d}" for i in range(500)]
        values = {n: int(rng.integers(0, 100)) for n in names}  # many ties
        ext = build_extender(values=values)
        for _ in range(5):
            subset = list(rng.choice(names, size=120, replace=False))
            body = args_body(subset, labels={"telemetry-policy": "pol"})
            native = ext.prioritize(request_from(body))
            monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
            python = ext.prioritize(request_from(body))
            monkeypatch.delenv("PAS_TPU_NO_NATIVE")
            assert native.body == python.body

    def test_planned_promotion_parity(self, monkeypatch):
        ext = build_extender()

        class StubPlanner:
            def planned_node(self, pod):
                return "n3"

        ext.planner = StubPlanner()
        body = args_body(["n1", "n2", "n3"], labels={"telemetry-policy": "pol"})
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert json.loads(native.body)[0]["Host"] == "n3"
        assert native.body == python.body


def build_filter_extender(values=None, target=50, node_cache_capable=True):
    """Extender with a dontschedule policy (GreaterThan target violates)
    over a device mirror, in NodeNames mode."""
    values = values or {"n1": 100, "n2": 50, "n3": 10, "n4": 70}
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default",
        "pol",
        TASPolicy.from_obj(
            make_policy(
                "pol",
                strategies={
                    "scheduleonmetric": [rule("m", "GreaterThan", 0)],
                    "dontschedule": [rule("m", "GreaterThan", target)],
                },
            )
        ),
    )
    cache.write_metric(
        "m", {n: NodeMetric(value=Quantity(str(v))) for n, v in values.items()}
    )
    return MetricsExtender(
        cache, mirror=mirror, node_cache_capable=node_cache_capable
    )


def nn_body(names, policy="pol") -> bytes:
    pod = {"metadata": {"name": "p", "namespace": "default"}}
    if policy is not None:
        pod["metadata"]["labels"] = {"telemetry-policy": policy}
    return json.dumps({"Pod": pod, "NodeNames": names}).encode()


class TestFilterNativeParity:
    """filter_encode (native NodeNames Filter path) must produce the exact
    bytes of the Python path for the same request."""

    # (names, native path expected) — the probe needs a non-empty
    # NodeNames list, so the empty case must take the exact path
    CASES = [
        (["n1", "n2", "n3", "n4"], True),       # mixed violating/passing
        (["n3", "n2"], True),                    # none violating
        (["n1", "n4"], True),                    # all violating
        (["n1", "ghost", "n3"], True),           # unknown name passes
        (["n1", "n1", "n4", "n2", "n1"], True),  # duplicate violators collapse
        ([""], True),                            # empty-string name
        ([], False),                             # empty list -> exact path
    ]

    @staticmethod
    def _spy_filter_encode(monkeypatch):
        """Count filter_encode invocations — the parity assertions are
        vacuous if a wiring bug silently degrades every request to the
        exact path (the probe's broad except would eat the error)."""
        calls = []
        real = wirec.filter_encode

        def spy(*args):
            calls.append(args)
            return real(*args)

        monkeypatch.setattr(wirec, "filter_encode", spy)
        return calls

    @pytest.mark.parametrize("case_idx", range(len(CASES)))
    def test_filter_nodenames_parity(self, case_idx, monkeypatch):
        names, native_expected = self.CASES[case_idx]
        body = nn_body(names)
        request = request_from(body)
        calls = self._spy_filter_encode(monkeypatch)
        native = build_filter_extender().filter(request)
        assert len(calls) == (1 if native_expected else 0), names
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = build_filter_extender().filter(request)
        assert native.status == python.status, names
        assert native.body == python.body, names

    def test_filter_escaped_unicode_names(self, monkeypatch):
        names = ['we"ird\\name', "uniécode", "plain", "tab\tname", "\x7f"]
        values = {n: (100 if i % 2 == 0 else 1) for i, n in enumerate(names)}
        body = nn_body(names + ["uniécode", 'we"ird\\name'])
        request = request_from(body)
        calls = self._spy_filter_encode(monkeypatch)
        native = build_filter_extender(values=values).filter(request)
        assert len(calls) == 1
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = build_filter_extender(values=values).filter(request)
        assert native.body == python.body
        assert b"FailedNodes" in native.body

    def test_filter_parity_at_scale(self, monkeypatch):
        rng = np.random.default_rng(7)
        names = [f"node-{i:04d}" for i in range(400)]
        values = {n: int(rng.integers(0, 100)) for n in names}
        calls = self._spy_filter_encode(monkeypatch)
        for trial in range(4):
            subset = list(rng.choice(names, size=150, replace=False))
            body = nn_body(subset)
            request = request_from(body)
            native = build_filter_extender(values=values).filter(request)
            assert len(calls) == trial + 1
            monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
            python = build_filter_extender(values=values).filter(request)
            monkeypatch.delenv("PAS_TPU_NO_NATIVE")
            assert native.body == python.body

    def test_filter_miss_then_hit_same_bytes(self, monkeypatch):
        """Two identical requests: first builds natively (miss), second is
        served from the span cache — byte-identical, one native encode."""
        calls = self._spy_filter_encode(monkeypatch)
        ext = build_filter_extender()
        request = request_from(nn_body(["n1", "n2", "n3"]))
        first = ext.filter(request)
        second = ext.filter(request)
        assert len(calls) == 1  # the second request was a span-cache hit
        assert first.body == second.body
        assert first.status == second.status == 200

    def test_filter_encode_mask_shorter_than_table_raises(self):
        parsed = wirec.parse_prioritize(nn_body(["n1"]))
        table = wirec.build_table(["n1", "n2"])
        with pytest.raises(ValueError):
            wirec.filter_encode(parsed, table, b"\x01")


class TestEncoderPoolConcurrency:
    """The process-wide buffer pool behind select_encode/filter_encode:
    many threads hammering both encoders (GIL-free sections overlap for
    real) must produce byte-correct output — a pooled buffer handed to
    two requests at once, or stale mask bytes surviving reuse, would
    corrupt responses."""

    def test_parallel_encoders_byte_correct(self):
        import threading

        rng = np.random.default_rng(3)
        n = 600
        names = [f"node-{i:04d}" for i in range(n)]
        table = wirec.build_table(names)
        ranked = np.argsort(
            rng.permutation(n), kind="stable"
        ).astype(np.int64)
        masks = [
            (rng.random(n) < p).astype(np.uint8).tobytes()
            for p in (0.0, 0.3, 0.9)
        ]
        subsets = []
        for _ in range(6):
            chosen = sorted(rng.choice(n, size=200, replace=False))
            body = json.dumps(
                {
                    "Pod": {"metadata": {"name": "p"}},
                    "NodeNames": [names[i] for i in chosen],
                }
            ).encode()
            subsets.append(body)
        # per-workload expected bytes computed single-threaded first
        expected = {}
        for bi, body in enumerate(subsets):
            parsed = wirec.parse_prioritize(body)
            expected[("sel", bi)] = wirec.select_encode(
                parsed, table, ranked, -1, True
            )
            for mi, mask in enumerate(masks):
                expected[("fil", bi, mi)] = wirec.filter_encode(
                    parsed, table, mask
                )
        errors = []

        def worker(seed):
            try:
                r = np.random.default_rng(seed)
                for _ in range(120):
                    bi = int(r.integers(len(subsets)))
                    parsed = wirec.parse_prioritize(subsets[bi])
                    if r.random() < 0.5:
                        got = wirec.select_encode(
                            parsed, table, ranked, -1, True
                        )
                        want = expected[("sel", bi)]
                    else:
                        mi = int(r.integers(len(masks)))
                        got = wirec.filter_encode(parsed, table, masks[mi])
                        want = expected[("fil", bi, mi)]
                    if got != want:
                        errors.append((seed, bi))
                        return
            except Exception as exc:  # a dying thread must fail the test
                errors.append((seed, repr(exc)))

        threads = [
            threading.Thread(target=worker, args=(s,)) for s in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors


class TestScannerStrictness:
    @pytest.mark.parametrize(
        "bad",
        [
            b'{"Pod": {,}}',
            b'{"Pod": {}} trailing',
            b'{"Pod": {"metadata": {"labels": {"telemetry-policy": 5}}}, "Nodes": {"items": []}}',
            b'{"Nodes": {"items": [{}',
            b'{"Nodes": {"items": 7}}',
            b'{"a": 01}',
            b'{"a": truthy}',
            b'{"a": "\x01"}',
        ],
    )
    def test_surprises_raise(self, bad):
        with pytest.raises((ValueError, TypeError)):
            wirec.parse_prioritize(bad)

    def test_whitespace_tolerated(self):
        body = b' \n\t{ "Pod" : { "metadata" : { "name" : "p" } } , "Nodes" : { "items" : [ { "metadata" : { "name" : "n1" } } ] } } \n'
        parsed = wirec.parse_prioritize(body)
        assert parsed.pod_name == "p"
        assert parsed.node_names() == ["n1"]

    def test_last_duplicate_key_wins(self):
        body = (
            b'{"Nodes": {"items": [{"metadata": {"name": "a"}}]},'
            b' "Nodes": {"items": [{"metadata": {"name": "b"}}]}}'
        )
        parsed = wirec.parse_prioritize(body)
        assert parsed.node_names() == ["b"]

    def test_select_encode_empty_selection(self):
        parsed = wirec.parse_prioritize(
            b'{"Nodes": {"items": [{"metadata": {"name": "ghost"}}]}}'
        )
        table = wirec.build_table(["n1", "n2"])
        ranked = np.array([0, 1], dtype=np.int64)
        assert wirec.select_encode(parsed, table, ranked) == b"[]\n"


class TestAdvisorFindings:
    """Round-2 advisor findings: malformed-string fallback, duplicate-key
    last-wins for Pod/metadata/labels, allocator hygiene."""

    @pytest.mark.parametrize(
        "body",
        [
            # invalid JSON escape inside the policy label value
            b'{"Pod": {"metadata": {"namespace": "default", "labels": '
            b'{"telemetry-policy": "\\q"}}}, '
            b'"Nodes": {"items": [{"metadata": {"name": "n1"}}]}}',
            # invalid UTF-8 inside the policy label value
            b'{"Pod": {"metadata": {"namespace": "default", "labels": '
            b'{"telemetry-policy": "\xff\xfe"}}}, '
            b'"Nodes": {"items": [{"metadata": {"name": "n1"}}]}}',
            # invalid UTF-8 inside a node name
            b'{"Pod": {"metadata": {"namespace": "default", "labels": '
            b'{"telemetry-policy": "pol"}}}, '
            b'"Nodes": {"items": [{"metadata": {"name": "n\xff1"}}]}}',
            # invalid escape inside the pod namespace
            b'{"Pod": {"metadata": {"namespace": "\\z", "labels": '
            b'{"telemetry-policy": "pol"}}}, '
            b'"Nodes": {"items": [{"metadata": {"name": "n1"}}]}}',
        ],
    )
    def test_malformed_string_bodies_answer_like_python(self, body, monkeypatch):
        # the verb must produce the same response as the exact Python path
        # (json.loads rejects these bodies -> empty 200), never an unhandled
        # exception / dropped connection
        ext = build_extender()
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.status == python.status
        assert native.body == python.body

    def test_duplicate_pod_key_last_wins(self):
        body = (
            b'{"Pod": {"metadata": {"name": "first", "namespace": "ns1", '
            b'"labels": {"telemetry-policy": "pol"}}}, '
            b'"Pod": {"metadata": {"name": "second"}}, '
            b'"Nodes": {"items": []}}'
        )
        parsed = wirec.parse_prioritize(body)
        obj = json.loads(body)  # python dict building is also last-wins
        assert parsed.pod_name == obj["Pod"]["metadata"]["name"] == "second"
        assert parsed.pod_namespace is None
        assert parsed.policy_label is None

    def test_duplicate_metadata_key_last_wins(self):
        body = (
            b'{"Pod": {"metadata": {"name": "first", '
            b'"labels": {"telemetry-policy": "pol"}}, '
            b'"metadata": {"namespace": "ns2"}}, "Nodes": {"items": []}}'
        )
        parsed = wirec.parse_prioritize(body)
        assert parsed.pod_name is None
        assert parsed.pod_namespace == "ns2"
        assert parsed.policy_label is None

    def test_duplicate_labels_key_last_wins(self):
        body = (
            b'{"Pod": {"metadata": {"labels": {"telemetry-policy": "old"}, '
            b'"labels": {"other": "x"}}}, "Nodes": {"items": []}}'
        )
        parsed = wirec.parse_prioritize(body)
        assert parsed.policy_label is None
        body2 = (
            b'{"Pod": {"metadata": {"labels": {"other": "x"}, '
            b'"labels": {"telemetry-policy": "new"}}}, "Nodes": {"items": []}}'
        )
        assert wirec.parse_prioritize(body2).policy_label == "new"

    def test_pod_null_after_object_has_no_effect(self):
        """Go decodes null into a VALUE struct (the reference's Args.Pod
        is v1.Pod by value) as "no effect" — fields captured from the
        earlier occurrence survive; the Python fold (_fold_keys nullable
        handling) and the native scanner agree."""
        body = (
            b'{"Pod": {"metadata": {"name": "first", '
            b'"labels": {"telemetry-policy": "pol"}}}, '
            b'"Pod": null, "Nodes": {"items": []}}'
        )
        parsed = wirec.parse_prioritize(body)
        assert parsed.pod_name == "first"
        assert parsed.policy_label == "pol"
        from platform_aware_scheduling_tpu.extender.types import Args

        args = Args.from_json(body)
        assert args.pod.name == "first"
        assert args.pod.get_labels()["telemetry-policy"] == "pol"

    def test_nodes_null_after_object_assigns_nil(self):
        """Pointer-typed Nodes/NodeNames DO take null (Go assigns nil)."""
        body = (
            b'{"NodeNames": ["n1"], "NodeNames": null, '
            b'"Pod": {"metadata": {"name": "p"}}}'
        )
        parsed = wirec.parse_prioritize(body)
        assert parsed.node_names_present == 0
        from platform_aware_scheduling_tpu.extender.types import Args

        args = Args.from_json(body)
        assert args.node_names is None

    def test_allocator_hygiene_under_debug_malloc(self):
        # NameTable mixes Buf (malloc) and PyMem storage; the dealloc must
        # free each with the matching allocator or PYTHONMALLOC=debug aborts
        import os
        import subprocess
        import sys

        code = (
            "from platform_aware_scheduling_tpu.native import get_wirec\n"
            "w = get_wirec()\n"
            "assert w is not None\n"
            "import numpy as np\n"
            "for _ in range(3):\n"
            "    t = w.build_table(['n%d' % i for i in range(500)])\n"
            "    p = w.parse_prioritize(b'{\"Nodes\": {\"items\": "
            "[{\"metadata\": {\"name\": \"n1\"}}]}}')\n"
            "    w.select_encode(p, t, np.arange(500, dtype=np.int64))\n"
            "    del t, p\n"
            "print('OK')\n"
        )
        env = dict(os.environ, PYTHONMALLOC="debug")
        env.pop("PAS_TPU_NO_NATIVE", None)
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=120,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert proc.returncode == 0, proc.stderr
        assert "OK" in proc.stdout

    def test_items_null_after_array_last_wins(self, monkeypatch):
        # {"items": [...], "items": null} -> json.loads keeps null; the
        # native parse must agree (and the verb must match the exact path)
        body = (
            b'{"Pod": {"metadata": {"namespace": "default", "labels": '
            b'{"telemetry-policy": "pol"}}}, '
            b'"Nodes": {"items": [{"metadata": {"name": "n1"}}], '
            b'"items": null}}'
        )
        parsed = wirec.parse_prioritize(body)
        assert parsed.num_nodes == 0
        ext = build_extender()
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.status == python.status
        assert native.body == python.body

    @pytest.mark.parametrize(
        "body",
        [
            # \u-escaped "Pod" alias: last-wins would pick the second, the
            # scanner cannot see that -> must fail and fall back
            b'{"Pod": {"metadata": {"name": "a"}}, '
            b'"\\u0050od": {"metadata": {"name": "b"}}, "Nodes": {"items": []}}',
            # escaped "metadata" inside Pod
            b'{"Pod": {"\\u006detadata": {"name": "x"}}, "Nodes": {"items": []}}',
            # escaped "items" inside Nodes
            b'{"Nodes": {"\\u0069tems": [{"metadata": {"name": "n"}}]}}',
        ],
    )
    def test_escaped_keys_fail_parse(self, body):
        with pytest.raises(ValueError):
            wirec.parse_prioritize(body)

    def test_scalar_key_last_wins_non_string(self, monkeypatch):
        # {"namespace": "default", "namespace": null}: json.loads keeps
        # null; the native parse must clear the earlier slice (and the verb
        # must answer exactly like the Python path, which misses the policy)
        body = (
            b'{"Pod": {"metadata": {"namespace": "default", "namespace": null, '
            b'"labels": {"telemetry-policy": "pol"}}}, '
            b'"Nodes": {"items": [{"metadata": {"name": "n1"}}]}}'
        )
        assert wirec.parse_prioritize(body).pod_namespace is None
        ext = build_extender()
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.status == python.status
        assert native.body == python.body

    def test_duplicate_node_metadata_last_wins(self):
        body = (
            b'{"Nodes": {"items": [{"metadata": {"name": "n1"}, '
            b'"metadata": {}}]}}'
        )
        parsed = wirec.parse_prioritize(body)
        # last-wins: the second metadata object has no name, which is the
        # Go zero value "" — exactly what the Python decode yields
        # (Node({}).name == ""); the round-5 differential fuzzer caught
        # the earlier drop-the-candidate behavior diverging
        assert parsed.node_names() == [""]

    def test_missing_name_is_empty_string_candidate(self):
        """A {} node item (or null metadata / null name) participates as
        the empty-named candidate on both paths — the Go zero value
        (fuzzer-found divergence, fixed in scan_node_item)."""
        parsed = wirec.parse_prioritize(
            b'{"Nodes": {"items": [{}, {"metadata": null}, '
            b'{"metadata": {"name": null}}]}}'
        )
        assert parsed.node_names() == ["", "", ""]

    def test_type_mismatches_fail_parse_like_go(self):
        """Go's json.Unmarshal fails the whole decode on type-mismatched
        fields; the scanner rejects identically so the exact path (whose
        from_json raises DecodeError -> the empty-200 quirk) owns the
        response on both runs."""
        import pytest

        for body in (
            b'{"Nodes": {"items": [{"metadata": {"name": 3}}]}}',
            b'{"Nodes": {"items": [{"metadata": 3}]}}',
            b'{"Pod": {"metadata": {"name": 3}}, "NodeNames": ["a"]}',
            b'{"Pod": {"metadata": {"namespace": []}}, "NodeNames": ["a"]}',
            b'{"Pod": {"metadata": {"labels": 3}}, "NodeNames": ["a"]}',
            b'{"Pod": {"metadata": {"labels": {"x": 3}}}, "NodeNames": ["a"]}',
        ):
            with pytest.raises(ValueError):
                wirec.parse_prioritize(body)

    @pytest.mark.parametrize(
        "bad",
        [
            b'{"a": "\\q"}',          # invalid escape
            b'{"a": "\\u12zz"}',      # bad \u hex
            b'{"a": "\xff"}',         # invalid UTF-8 lead byte
            b'{"a": "\xc0\xaf"}',     # overlong encoding
            b'{"a": "\xf5\x80\x80\x80"}',  # > U+10FFFF
            b'{"a": "\xc3"}',         # truncated sequence at end of string
        ],
    )
    def test_strings_validated_like_json_loads(self, bad):
        # every body here is also rejected by json.loads on bytes
        with pytest.raises(ValueError):
            json.loads(bad)
        with pytest.raises(ValueError):
            wirec.parse_prioritize(bad)

    def test_valid_unicode_zero_copy(self):
        # valid non-ASCII stays on the zero-copy path (escaped=0) and
        # round-trips through name lookup byte-exactly
        name = "nodé-ü"
        body = json.dumps(
            {"Nodes": {"items": [{"metadata": {"name": name}}]}},
            ensure_ascii=False,
        ).encode()
        parsed = wirec.parse_prioritize(body)
        assert parsed.node_names() == [name]
        table = wirec.build_table([name])
        out = wirec.select_encode(parsed, table, np.array([0], dtype=np.int64))
        assert json.loads(out) == [{"Host": name, "Score": 10}]

    def test_surrogate_bytes_fall_back_with_parity(self, monkeypatch):
        # json.loads(bytes) decodes with surrogatepass, so a UTF-8-encoded
        # lone surrogate is ACCEPTED by the Python path; the scanner
        # rejects it (-> fallback), which is parity-safe because the exact
        # path then owns the whole answer
        body = (
            b'{"Pod": {"metadata": {"namespace": "default", "labels": '
            b'{"telemetry-policy": "pol"}}}, '
            b'"Nodes": {"items": [{"metadata": {"name": "n1"}}, '
            b'{"metadata": {"name": "s\xed\xa0\x80x"}}]}}'
        )
        json.loads(body)  # accepted by the Python decoder
        with pytest.raises(ValueError):
            wirec.parse_prioritize(body)
        ext = build_extender()
        native = ext.prioritize(request_from(body))
        monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
        python = ext.prioritize(request_from(body))
        assert native.status == python.status
        assert native.body == python.body

"""Flight recorder + trace replay gates (ISSUE 13, docs/observability.md
"Flight recorder & what-if").

Four contracts pinned here, not merely promised in docstrings:

  * anonymization — a serialized capture NEVER contains a node, pod, or
    namespace name (grepped against every name the traffic used);
  * off-path neutrality — with no recorder wired the verb responses are
    byte-identical on the wire to a recorder-on build (modulo the
    per-request X-Request-ID) and /metrics emits no pas_record_*
    families at all;
  * round-trip fidelity — a capture exported over real sockets parses
    back into the exact event stream, and a twin-recorded diurnal run
    replayed through ReplayScenario reproduces the source run's SLO
    verdicts (ReplayedDiurnal);
  * bounded hot-path cost — the recorder's per-request delta, measured
    hermetically in-process with interleaved on/off batches, stays far
    under the <=5% p99 budget the wire A/B contextualizes.
"""

import json

import numpy as np
import pytest

from benchmarks.http_load import (
    build_extender,
    make_bodies,
    record_inprocess_overhead,
)
from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.testing import replay
from platform_aware_scheduling_tpu.testing.ha import METRIC, POD_LOAD
from platform_aware_scheduling_tpu.testing.twin import TwinCluster
from platform_aware_scheduling_tpu.utils import trace
from platform_aware_scheduling_tpu.utils.record import (
    FORMAT,
    FlightRecorder,
    decile_summary,
)
from wirehelpers import (
    get_request,
    post_bytes,
    raw_request,
    start_async,
    start_threaded,
)


def verb_request(path: str, body: bytes) -> HTTPRequest:
    return HTTPRequest(
        method="POST",
        path=path,
        headers={"Content-Type": "application/json"},
        body=body,
    )


def synth_recorder(
    ticks: int = 4,
    nodes: int = 8,
    verbs_per_tick: int = 4,
    period: float = 5.0,
    lo: float = 100.0,
    hi: float = 800.0,
) -> FlightRecorder:
    """A deterministic fake-clock capture: one telemetry pass per tick
    over a linear load ramp, ``verbs_per_tick`` verb arrivals inside
    each tick's window."""
    state = {"t": 0.0}
    rec = FlightRecorder(capacity=4096, clock=lambda: state["t"])
    values = [
        lo + (hi - lo) * i / max(1, nodes - 1) for i in range(nodes)
    ]
    for tick in range(ticks):
        state["t"] = tick * period
        rec.record_telemetry(METRIC, values)
        for v in range(verbs_per_tick):
            state["t"] = tick * period + 0.2 * (v + 1)
            rec.record_verb(
                "prioritize" if v % 2 == 0 else "filter",
                universe_uid=0xDEADBEEF,
                candidates=nodes,
            )
    return rec


# ---------------------------------------------------------------------------
# recorder units
# ---------------------------------------------------------------------------


class TestDecileSummary:
    def test_empty_is_none(self):
        assert decile_summary([]) is None

    def test_single_value_is_flat_curve(self):
        assert decile_summary([7.0]) == [7.0] * 11

    def test_linear_ramp_interpolates_exactly(self):
        assert decile_summary(range(11)) == [float(i) for i in range(11)]

    def test_unsorted_input_and_rounding(self):
        curve = decile_summary([3.0001, 1.0, 2.0])
        assert curve[0] == 1.0
        assert curve[-1] == 3.0
        assert all(round(v, 3) == v for v in curve)


class TestFlightRecorder:
    def test_verb_event_fields_are_anonymous(self):
        rec = FlightRecorder(clock=lambda: 12.5)
        rec.record_verb("prioritize", universe_uid=0x1234, candidates=3)
        (event,) = rec.events()
        assert set(event) == {"t", "kind", "verb", "universe", "candidates"}
        assert event["t"] == 12.5
        assert event["verb"] == "prioritize"
        assert event["universe"] == "0000000000001234"
        assert event["candidates"] == 3

    def test_gang_size_key_only_when_nonzero(self):
        rec = FlightRecorder(clock=lambda: 0.0)
        rec.record_verb("filter", gang_size=0)
        rec.record_verb("filter", gang_size=4)
        first, second = rec.events()
        assert "gang_size" not in first
        assert second["gang_size"] == 4

    def test_cold_span_universe_is_null(self):
        rec = FlightRecorder(clock=lambda: 0.0)
        rec.record_verb("filter", universe_uid=None, candidates=9)
        assert rec.events()[0]["universe"] is None

    def test_negative_uid_masks_to_64_bits(self):
        rec = FlightRecorder(clock=lambda: 0.0)
        rec.record_verb("filter", universe_uid=-1)
        assert rec.events()[0]["universe"] == "f" * 16

    def test_ring_keeps_latest_window_and_counts_drops(self):
        rec = FlightRecorder(capacity=4, clock=lambda: 0.0)
        for i in range(6):
            rec.record_verb("prioritize", candidates=i)
        events = rec.events()
        assert [e["candidates"] for e in events] == [2, 3, 4, 5]
        snap = rec.snapshot()
        assert snap["events"] == 4
        assert snap["dropped"] == 2
        assert rec.counters.get("pas_record_events_total") == 6
        assert rec.counters.get("pas_record_dropped_total") == 2

    def test_empty_telemetry_pass_records_nothing(self):
        rec = FlightRecorder(clock=lambda: 0.0)
        rec.record_telemetry("node_load", [])
        rec.record_eviction(0)
        rec.record_eviction(-3)
        assert rec.events() == []

    def test_jsonl_framing_round_trips(self):
        rec = synth_recorder(ticks=2, verbs_per_tick=2)
        payload = rec.to_jsonl()
        assert payload.endswith(b"\n")
        lines = payload.decode().splitlines()
        header = json.loads(lines[0])
        assert header["format"] == FORMAT
        assert header["events"] == len(lines) - 1
        assert header["dropped"] == 0
        kinds = {json.loads(line)["kind"] for line in lines[1:]}
        assert kinds == {"telemetry", "verb"}

    def test_poll_control_diffs_fleet_counters(self):
        rec = FlightRecorder(clock=lambda: 0.0)
        # poll_control sums the whole pas_leader family (a fleet has a
        # leader, whichever replica label carries it) — drop series left
        # behind by earlier HA/twin tests so this test owns the family
        trace.COUNTERS.remove("pas_leader", kind="gauge")
        trace.COUNTERS.set_gauge("pas_leader", 1.0)
        rec.poll_control()
        # the FIRST observation is itself an event: the capture says
        # which role the window started in
        leaders = [e for e in rec.events() if e["kind"] == "leader"]
        assert leaders and leaders[-1]["leader"] is True
        before = len(rec.events())
        rec.poll_control()  # no movement -> no event
        assert len(rec.events()) == before
        trace.COUNTERS.inc("pas_rebalance_moves_executed_total", 2)
        trace.COUNTERS.set_gauge("pas_leader", 0.0)
        rec.poll_control()
        evictions = [e for e in rec.events() if e["kind"] == "eviction"]
        assert evictions and evictions[-1]["count"] == 2
        leaders = [e for e in rec.events() if e["kind"] == "leader"]
        assert leaders[-1]["leader"] is False


# ---------------------------------------------------------------------------
# the wire: /debug/record, /debug/whatif, off-path neutrality
# ---------------------------------------------------------------------------


def _start(front_end, ext):
    return start_async(ext) if front_end == "async" else start_threaded(ext)


@pytest.mark.parametrize("front_end", ["threaded", "async"])
class TestRecordEndpoint:
    def test_record_404_when_off(self, front_end):
        ext, _names = build_extender(8, device=True)
        server = _start(front_end, ext)
        try:
            status, _, body = get_request(server.port, "/debug/record")
            assert status == 404
            assert "flight recorder" in json.loads(body)["error"]
        finally:
            server.shutdown()

    def test_record_serves_capture_after_traffic(self, front_end):
        ext, names = build_extender(8, device=True)
        ext.flight = FlightRecorder()
        server = _start(front_end, ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            for path in ("/scheduler/prioritize", "/scheduler/filter"):
                status, _, _ = raw_request(
                    server.port, post_bytes(path, body)
                )
                assert status == 200
            status, headers, payload = get_request(
                server.port, "/debug/record"
            )
            assert status == 200
            assert headers["content-type"] == "application/x-ndjson"
            lines = [
                json.loads(line)
                for line in payload.decode().splitlines()
            ]
            assert lines[0]["format"] == FORMAT
            verbs = [
                e for e in lines[1:] if e.get("kind") == "verb"
            ]
            assert {e["verb"] for e in verbs} == {"prioritize", "filter"}
            assert all(e["candidates"] == len(names) for e in verbs)
            # POST against the GET-only export must 405
            status, _, _ = raw_request(
                server.port, post_bytes("/debug/record", b"{}")
            )
            assert status == 405
        finally:
            server.shutdown()


class TestWhatifEndpoint:
    def test_whatif_404_when_off_and_405_on_get(self):
        ext, _names = build_extender(8, device=True)
        server = start_threaded(ext)
        try:
            status, _, body = raw_request(
                server.port, post_bytes("/debug/whatif", b"{}")
            )
            assert status == 404
            assert "flight recorder" in json.loads(body)["error"]
            ext.flight = FlightRecorder()
            status, _, _ = get_request(server.port, "/debug/whatif")
            assert status == 405
        finally:
            server.shutdown()

    def test_whatif_rejects_bad_specs(self):
        ext, _names = build_extender(8, device=True)
        ext.flight = FlightRecorder()
        server = start_threaded(ext)
        try:
            for bad in (b"[1, 2]", b"not json"):
                status, _, body = raw_request(
                    server.port, post_bytes("/debug/whatif", bad)
                )
                assert status == 400
                assert "JSON object" in json.loads(body)["error"]
            status, _, body = raw_request(
                server.port,
                post_bytes("/debug/whatif", b'{"load_mult": 2}'),
            )
            assert status == 400
            assert "load_mult" in json.loads(body)["error"]
            # an empty live ring has no telemetry passes to anchor on
            status, _, body = raw_request(
                server.port, post_bytes("/debug/whatif", b"{}")
            )
            assert status == 400
            assert "telemetry" in json.loads(body)["error"]
            assert (
                trace.COUNTERS.get("pas_whatif_failures_total") >= 3
            )
        finally:
            server.shutdown()

    def test_whatif_projects_verdicts_from_live_ring(self):
        ext, names = build_extender(8, device=True)
        # register the metric so observe_cache's telemetry pass sees it
        # (production assembly registers through the policy watcher)
        ext.cache.write_metric("load_metric")
        clk = {"t": 0.0}
        flight = FlightRecorder(clock=lambda: clk["t"])
        ext.flight = flight
        server = start_threaded(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            for tick in range(3):
                clk["t"] = tick * 5.0
                for path in (
                    "/scheduler/prioritize",
                    "/scheduler/filter",
                ):
                    status, _, _ = raw_request(
                        server.port, post_bytes(path, body)
                    )
                    assert status == 200
                flight.observe_cache(ext.cache)
            runs_before = trace.COUNTERS.get("pas_whatif_runs_total")
            status, _, payload = raw_request(
                server.port,
                post_bytes("/debug/whatif", b'{"max_ticks": 2}'),
            )
            assert status == 200
            result = json.loads(payload)
            assert result["format"] == FORMAT
            assert result["capture"]["num_nodes"] == len(names)
            assert result["scale"]["ticks"] == 2
            assert result["traffic"]["requests"] > 0
            assert result["verdicts"]
            for entry in result["verdicts"].values():
                assert "alert" in entry and "compliance" in entry
            assert (
                trace.COUNTERS.get("pas_whatif_runs_total")
                == runs_before + 1
            )
        finally:
            server.shutdown()

    def test_whatif_accepts_inline_capture(self):
        ext, _names = build_extender(8, device=True)
        ext.flight = FlightRecorder()  # wired but empty: spec supplies
        server = start_threaded(ext)
        try:
            spec = json.dumps(
                {
                    "capture": synth_recorder(ticks=2)
                    .to_jsonl()
                    .decode(),
                    "max_ticks": 2,
                }
            ).encode()
            status, _, payload = raw_request(
                server.port, post_bytes("/debug/whatif", spec)
            )
            assert status == 200
            result = json.loads(payload)
            assert result["capture"]["metric"] == METRIC
            assert result["scale"]["num_nodes"] == 8
        finally:
            server.shutdown()


class TestOffPathNeutrality:
    def test_verb_responses_byte_identical_with_and_without_recorder(
        self,
    ):
        """The recorder must never touch a verb response: the same
        request against a recorder-off and a recorder-on build returns
        the same status, the same body, and the same headers (only the
        per-request X-Request-ID may differ)."""
        wire = {}
        for label, flight in (("off", None), ("on", FlightRecorder())):
            ext, names = build_extender(12, device=True)
            ext.flight = flight
            server = start_threaded(ext)
            try:
                body = make_bodies(names, "nodenames", count=1)[0]
                wire[label] = {
                    path: raw_request(
                        server.port, post_bytes(path, body)
                    )
                    for path in (
                        "/scheduler/prioritize",
                        "/scheduler/filter",
                    )
                }
            finally:
                server.shutdown()
        for path, (status, headers, body) in wire["off"].items():
            on_status, on_headers, on_body = wire["on"][path]
            assert status == on_status == 200
            assert body == on_body
            drop = "x-request-id"
            assert {k: v for k, v in headers.items() if k != drop} == {
                k: v for k, v in on_headers.items() if k != drop
            }

    def test_metrics_families_follow_the_recorder(self):
        ext, names = build_extender(8, device=True)
        body = make_bodies(names, "nodenames", count=1)[0]
        ext.prioritize(verb_request("/scheduler/prioritize", body))
        assert "pas_record_" not in ext.metrics_text()
        # capacity 1 so the second event also overflows the ring: both
        # record families land on the exposition in one pass
        ext.flight = FlightRecorder(capacity=1)
        ext.prioritize(verb_request("/scheduler/prioritize", body))
        ext.prioritize(verb_request("/scheduler/prioritize", body))
        text = ext.metrics_text()
        assert "pas_record_events_total" in text
        assert "pas_record_dropped_total" in text


class TestAnonymization:
    def test_capture_never_contains_cluster_names(self):
        """The contract docs/observability.md promises: drive real
        traffic carrying node, pod, and namespace names, run a full
        telemetry pass, and grep the serialized capture for every one
        of them — zero hits."""
        ext, names = build_extender(24, device=True)
        flight = FlightRecorder()
        ext.flight = flight
        server = start_threaded(ext)
        try:
            for body in make_bodies(names, "nodenames", count=4):
                for path in (
                    "/scheduler/prioritize",
                    "/scheduler/filter",
                ):
                    status, _, _ = raw_request(
                        server.port, post_bytes(path, body)
                    )
                    assert status == 200
            flight.observe_cache(ext.cache)
        finally:
            server.shutdown()
        payload = flight.to_jsonl()
        assert flight.events(), "capture must not be empty"
        for name in names:
            assert name.encode() not in payload, name
        assert b"node-" not in payload
        assert b"bench-pod" not in payload  # the driven pod names
        assert b"default" not in payload  # the driven namespace
        # and the positive side: verb events carry only the digest/count
        for event in flight.events():
            if event["kind"] == "verb":
                universe = event["universe"]
                assert universe is None or (
                    len(universe) == 16
                    and int(universe, 16) >= 0
                )


# ---------------------------------------------------------------------------
# replay + what-if units
# ---------------------------------------------------------------------------


class TestParseCapture:
    def test_rejects_unreplayable_sources(self):
        with pytest.raises(replay.CaptureError):
            replay.parse_capture("")
        with pytest.raises(replay.CaptureError):
            replay.parse_capture("not json\n")
        with pytest.raises(replay.CaptureError):
            replay.parse_capture('{"format": "pas-flight-record/999"}\n')
        with pytest.raises(replay.CaptureError):
            replay.parse_capture({"no_events": True})
        with pytest.raises(replay.CaptureError):
            replay.parse_capture(42)
        # a capture with no telemetry passes has no replay timeline
        rec = FlightRecorder(clock=lambda: 0.0)
        rec.record_verb("prioritize")
        with pytest.raises(replay.CaptureError):
            replay.parse_capture(rec)

    def test_timeline_inference_from_synthetic_capture(self):
        capture = replay.parse_capture(
            synth_recorder(ticks=4, nodes=8, verbs_per_tick=4)
        )
        assert capture.metric == METRIC
        assert capture.tick_count == 4
        assert capture.num_nodes == 8
        assert capture.period_s == 5.0
        assert capture.arrivals == [4, 4, 4, 4]
        assert capture.floor_load == 100.0
        stats = capture.stats()
        assert stats["verbs"] == {"filter": 8, "prioritize": 8}
        assert stats["peak_verbs_per_tick"] == 4
        assert stats["ticks"] == 4

    def test_jsonl_and_dict_and_recorder_sources_agree(self):
        rec = synth_recorder(ticks=2)
        from_rec = replay.parse_capture(rec)
        from_jsonl = replay.parse_capture(rec.to_jsonl())
        from_dict = replay.parse_capture(
            {"format": FORMAT, "events": rec.events()}
        )
        for capture in (from_jsonl, from_dict):
            assert capture.stats() == from_rec.stats()

    def test_long_capture_streams_past_the_old_tick_cap(self):
        """The streaming parser contract (ISSUE 15): a capture longer
        than the pre-lift 2000-tick cap round-trips through every JSONL
        shape — str, bytes, and a lazy line generator (an open file) —
        without materializing the text, and ReplayScenario accepts the
        full timeline under the lifted 20000-tick cap."""
        assert replay.MAX_REPLAY_TICKS == 20000
        ticks = 5000
        lines = [
            json.dumps({"format": FORMAT, "dropped": 0})
        ]
        for tick in range(ticks):
            lines.append(json.dumps({
                "kind": "telemetry",
                "t": tick * 5.0,
                "metric": METRIC,
                "nodes": 4,
                "deciles": [100.0] * 11,
            }))
        text = "\n".join(lines) + "\n"
        from_str = replay.parse_capture(text)
        from_bytes = replay.parse_capture(text.encode("utf-8"))
        # a generator of lines — the open-file shape; nothing concatenated
        from_stream = replay.parse_capture(
            line + "\n" for line in lines
        )
        assert from_str.tick_count == ticks
        for capture in (from_bytes, from_stream):
            assert capture.stats() == from_str.stats()
        scenario = replay.ReplayScenario(from_str, num_nodes=4)
        assert scenario.ticks_n == ticks  # not clamped at the old 2000


class TestWhatif:
    def test_spec_validation(self):
        with pytest.raises(replay.CaptureError, match="unknown"):
            replay.whatif_from_spec({"typo_knob": 1})
        with pytest.raises(replay.CaptureError, match="self"):
            replay.whatif_from_spec({})  # no live recorder
        with pytest.raises(replay.CaptureError, match="number"):
            replay.whatif_from_spec(
                {"capture": "x", "load_multiplier": True}
            )
        with pytest.raises(replay.CaptureError, match="capture"):
            replay.whatif_from_spec({"capture": 7})

    def test_double_load_degrades_availability(self):
        """The acceptance demo: the recorded peak becomes the admission
        budget, so a 1x replay sheds nothing and a 2x what-if saturates
        it — the availability SLO must degrade."""
        rec = synth_recorder(ticks=4, nodes=8, verbs_per_tick=4)
        base = replay.whatif(rec, load_multiplier=1.0)
        doubled = replay.whatif(rec, load_multiplier=2.0)
        assert base["traffic"]["errors"] == 0
        assert doubled["traffic"]["errors"] > 0
        avail = [
            name
            for name in base["verdicts"]
            if "availability" in name
        ]
        assert avail, sorted(base["verdicts"])
        for name in avail:
            assert (
                doubled["verdicts"][name]["compliance"]
                < base["verdicts"][name]["compliance"]
            )

    def test_remove_nodes_shrinks_the_replay_fleet(self):
        rec = synth_recorder(ticks=2, nodes=8)
        out = replay.whatif(rec, remove_nodes=3)
        assert out["scale"]["num_nodes"] == 5
        assert out["transform"]["remove_nodes"] == 3


# ---------------------------------------------------------------------------
# round-trip fidelity
# ---------------------------------------------------------------------------


class TestRoundTrip:
    def test_socket_export_round_trips_into_a_running_replay(self):
        """Capture over a REAL socket -> parse -> ReplayScenario run:
        the stats round-trip exactly and the replayed twin judges
        traffic — the full production path of the what-if feature."""
        ext, names = build_extender(8, device=True)
        ext.cache.write_metric("load_metric")
        clk = {"t": 0.0}
        flight = FlightRecorder(clock=lambda: clk["t"])
        ext.flight = flight
        server = start_threaded(ext)
        try:
            body = make_bodies(names, "nodenames", count=1)[0]
            for tick in range(3):
                clk["t"] = tick * 5.0
                for path in (
                    "/scheduler/prioritize",
                    "/scheduler/filter",
                ):
                    status, _, _ = raw_request(
                        server.port, post_bytes(path, body)
                    )
                    assert status == 200
                flight.observe_cache(ext.cache)
            status, _, payload = get_request(
                server.port, "/debug/record"
            )
            assert status == 200
        finally:
            server.shutdown()
        capture = replay.parse_capture(payload)
        assert capture.stats() == replay.parse_capture(flight).stats()
        assert capture.num_nodes == len(names)
        assert capture.period_s == 5.0
        verdict = replay.ReplayScenario(capture, max_ticks=2).run()
        assert all(c["ok"] for c in verdict["checks"]), verdict["checks"]
        assert verdict["traffic"]["requests"] > 0

    def test_replayed_diurnal_reproduces_source_verdicts(self):
        """The fidelity gate itself: record a diurnal twin run through
        the production wiring, replay the capture, and require the same
        per-SLO alert tiers + compliance and the same final decile
        curve."""
        verdict = replay.ReplayedDiurnal().run()
        assert verdict["checks"], "fidelity run produced no checks"
        for check in verdict["checks"]:
            assert check["ok"], check
        names = {c["check"] for c in verdict["checks"]}
        assert "round_trip_scale" in names
        assert "decile_round_trip" in names
        assert any(n.startswith("fidelity:") for n in names)


# ---------------------------------------------------------------------------
# the vectorized twin
# ---------------------------------------------------------------------------


class TestVectorizedTwin:
    def _payload(self, twin):
        info = twin.metrics.get_node_metric(METRIC)
        return {
            name: metric.value.milli_value_exact()[0]
            for name, metric in info.items()
        }

    def test_vectorized_publication_matches_legacy(self):
        base = {f"node-{i}": 37 * i for i in range(10)}
        payloads = {}
        for mode in (False, True):
            twin = TwinCluster(
                num_nodes=10,
                pods=20,
                gas=False,
                vectorized=mode,
                seed=3,
            )
            try:
                twin.set_base_load(base)
                twin.publish_loads()
                payloads[mode] = self._payload(twin)
                twin.fail_nodes(["node-3"])
                twin.publish_loads()
                payloads[(mode, "failed")] = self._payload(twin)
            finally:
                twin.close()
        assert payloads[True] == payloads[False]
        assert payloads[(True, "failed")] == payloads[(False, "failed")]
        assert "node-3" not in payloads[(True, "failed")]
        # placement-derived pod load is visible on top of base load
        assert payloads[True]["node-0"] == 2 * POD_LOAD * 1000

    def test_set_base_load_vector_clamps_and_syncs(self):
        twin = TwinCluster(num_nodes=4, pods=0, gas=False, seed=3)
        try:
            twin.set_base_load_vector(np.array([50, -10, 75]))
            assert twin.base_load == {
                "node-0": 50,
                "node-1": 0,  # negative interpolation targets clamp
                "node-2": 75,
                "node-3": 0,  # short vectors zero-fill
            }
            twin.set_base_load_vector(np.arange(10))  # long: truncated
            assert twin.base_load["node-3"] == 3
        finally:
            twin.close()

    def test_serving_capacity_sheds_and_counts(self):
        twin = TwinCluster(
            num_nodes=4,
            pods=4,
            gas=False,
            serving_capacity=2,
            requests_per_tick=3,
            seed=3,
        )
        try:
            twin.tick()
            # 3 pairs = 6 verb requests against a budget of 2
            assert twin.traffic["requests"] == 6
            assert twin.traffic["errors"] == 4
            assert (
                twin.serving_counters.get("pas_serving_rejected_total")
                == 4
            )
        finally:
            twin.close()


# ---------------------------------------------------------------------------
# hot-path cost + CLI
# ---------------------------------------------------------------------------


class TestRecorderOverhead:
    def test_in_process_delta_stays_in_budget(self):
        """The hermetic form of the <=5% p99 acceptance bound: the
        recorder's per-request delta (interleaved on/off batches,
        median of batch means, gc fenced) must stay far below the
        ~200 us a 10k-node verb costs — 50 us is >5x the measured
        ~4-8 us and still well under the budget."""
        out = record_inprocess_overhead(
            num_nodes=2000, batches=10, per_batch=30
        )
        for verb in ("prioritize", "filter"):
            delta = out[f"{verb}_delta_us"]
            assert delta < 50.0, out


class TestWhatifCLI:
    def test_cli_projects_from_a_capture_file(self, tmp_path, capsys):
        from platform_aware_scheduling_tpu.cmd.whatif import main

        path = tmp_path / "capture.jsonl"
        path.write_bytes(synth_recorder(ticks=2).to_jsonl())
        code = main(
            ["--capture", str(path), "--maxTicks", "2",
             "--loadMultiplier", "2.0"]
        )
        assert code == 0
        result = json.loads(capsys.readouterr().out)
        assert result["format"] == FORMAT
        assert result["transform"]["load_multiplier"] == 2.0
        assert result["verdicts"]

    def test_cli_fails_cleanly(self, tmp_path, capsys):
        from platform_aware_scheduling_tpu.cmd.whatif import main

        assert main(["--capture", str(tmp_path / "missing.jsonl")]) == 2
        assert "error:" in capsys.readouterr().err
        bad = tmp_path / "bad.jsonl"
        bad.write_text("not json\n")
        assert main(["--capture", str(bad)]) == 2


# ---------------------------------------------------------------------------
# scale (slow tier)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestVectorizedTickScale:
    def test_vectorized_tick_beats_legacy_at_100k(self):
        """ISSUE 13's speed gate, at full scale: the vectorized tick
        must hold an absolute budget (<=1 s/tick at 100k nodes, vs the
        ~5 s/tick seed baseline) and beat the in-tree legacy path by
        >=3x (the switch isolates exactly the vectorized load model)."""
        import time

        rates = {}
        for mode in (False, True):
            twin = TwinCluster(
                num_nodes=100_000,
                pods=200_000,
                gas=False,
                slo=False,
                vectorized=mode,
                requests_per_tick=0,
                seed=3,
            )
            try:
                twin.tick()  # warm caches/JIT outside the window
                t0 = time.perf_counter()
                for _ in range(3):
                    twin.tick()
                rates[mode] = (time.perf_counter() - t0) / 3
            finally:
                twin.close()
        assert rates[True] <= 1.0, rates
        assert rates[False] / rates[True] >= 3.0, rates

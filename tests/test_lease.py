"""Leader election over the Lease subresource (kube/lease.py) and the
FakeKubeClient lease conflict semantics it builds on
(docs/robustness.md "HA & leader election").

Everything runs on fake clocks; nothing sleeps.  The contract under
test, bottom up: the fake's optimistic concurrency (stale
resourceVersion -> 409, concurrent acquirers -> exactly one winner),
the elector's lifecycle (acquire, renew, takeover with a bumped fencing
token, local expiry, fencing checks), the retry stack's
idempotent-by-fencing classification of the lease verbs, and the
/debug/leader surface on both front-ends.
"""

import json

import pytest

from platform_aware_scheduling_tpu.kube.client import (
    ConflictError,
    NotFoundError,
)
from platform_aware_scheduling_tpu.kube.lease import LeaseElector
from platform_aware_scheduling_tpu.kube.retry import (
    CircuitBreakerRegistry,
    FENCED_WRITE_VERBS,
    FaultTolerantClient,
    READ_VERBS,
    RetryPolicy,
    WRITE_VERBS,
    backoff_delay,
    stable_hash,
)
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.testing.faults import FakeClock, FaultPlan
from platform_aware_scheduling_tpu.utils import trace
from wirehelpers import get_request, post_bytes, raw_request, start_async, start_threaded


def _lease(name="l", holder="x", rv=None, transitions=1):
    obj = {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "holderIdentity": holder,
            "leaseDurationSeconds": 10.0,
            "renewTime": 0.0,
            "leaseTransitions": transitions,
        },
    }
    if rv is not None:
        obj["metadata"]["resourceVersion"] = rv
    return obj


class TestFakeLeaseSemantics:
    def test_get_missing_is_404(self):
        fake = FakeKubeClient()
        with pytest.raises(NotFoundError):
            fake.get_lease("default", "nope")

    def test_create_existing_is_409(self):
        fake = FakeKubeClient()
        fake.create_lease(_lease())
        with pytest.raises(ConflictError):
            fake.create_lease(_lease())

    def test_update_with_stale_resource_version_is_409(self):
        fake = FakeKubeClient()
        created = fake.create_lease(_lease())
        stale_rv = created["metadata"]["resourceVersion"]
        # a first update commits and bumps the RV...
        fresh = fake.update_lease(_lease(rv=stale_rv, holder="y"))
        assert fresh["metadata"]["resourceVersion"] != stale_rv
        # ...so replaying the old RV is the classic lost-update conflict
        with pytest.raises(ConflictError):
            fake.update_lease(_lease(rv=stale_rv, holder="z"))

    def test_update_missing_is_404(self):
        fake = FakeKubeClient()
        with pytest.raises(NotFoundError):
            fake.update_lease(_lease(rv="1"))

    def test_concurrent_acquirers_exactly_one_winner(self):
        """N electors racing an empty lease: exactly one create commits;
        the rest observe the conflict and follow."""
        fake = FakeKubeClient()
        clock = FakeClock()
        electors = [
            LeaseElector(fake, f"r{i}", lease_name="l", clock=clock.now)
            for i in range(5)
        ]
        outcomes = [e.tick() for e in electors]
        assert sum(outcomes) == 1
        assert [e.is_leader() for e in electors].count(True) == 1
        # and the race for a takeover of an EXPIRED lease is just as
        # exclusive: both contenders observed the same stale RV
        clock.advance(1000.0)
        followers = [e for e in electors if not e.is_leader()]
        winners = [e.tick() for e in followers]
        assert sum(winners) == 1

    def test_configmap_conflict_semantics_match(self):
        fake = FakeKubeClient()
        cm = {
            "metadata": {"name": "j", "namespace": "default"},
            "data": {"state": "{}"},
        }
        created = fake.create_configmap(dict(cm))
        with pytest.raises(ConflictError):
            fake.create_configmap(dict(cm))
        stale = created["metadata"]["resourceVersion"]
        fake.update_configmap(
            {"metadata": {"name": "j", "namespace": "default",
                          "resourceVersion": stale}, "data": {"state": "1"}}
        )
        with pytest.raises(ConflictError):
            fake.update_configmap(
                {"metadata": {"name": "j", "namespace": "default",
                              "resourceVersion": stale}, "data": {}}
            )


class TestLeaseElector:
    def test_acquire_renew_keeps_token(self):
        fake = FakeKubeClient()
        clock = FakeClock()
        elector = LeaseElector(
            fake, "a", lease_name="l", lease_duration_s=10.0, clock=clock.now
        )
        assert elector.tick() is True
        assert elector.fencing_token() == 1
        for _ in range(5):
            clock.advance(3.0)
            assert elector.tick() is True
        # renewing is not a transition: the token is stable
        assert elector.fencing_token() == 1

    def test_takeover_after_expiry_bumps_fencing_token(self):
        fake = FakeKubeClient()
        clock = FakeClock()
        a = LeaseElector(fake, "a", lease_name="l", lease_duration_s=10.0,
                         clock=clock.now)
        b = LeaseElector(fake, "b", lease_name="l", lease_duration_s=10.0,
                         clock=clock.now)
        a.tick()
        assert b.tick() is False  # live holder: follow
        clock.advance(10.0)  # a's grant lapses un-renewed
        assert b.tick() is True
        assert b.fencing_token() == 2
        # a demoted itself locally the moment its own deadline passed
        assert a.is_leader() is False
        assert a.fencing_token() is None

    def test_local_expiry_during_api_outage(self):
        """An unrenewable leader steps down by ITSELF: no API contact is
        needed for is_leader() to go false once its grant would have
        lapsed — the singleton loops stop before a takeover is legal."""
        fake = FakeKubeClient()
        clock = FakeClock()
        plan = FaultPlan()
        fake.fault_plan = plan
        fake.fault_clock = clock
        elector = LeaseElector(
            fake, "a", lease_name="l", lease_duration_s=10.0, clock=clock.now
        )
        elector.tick()
        plan.outage("get_lease", status=503)
        plan.outage("update_lease", status=503)
        clock.advance(5.0)
        elector.tick()  # renew fails; grant still within duration
        assert elector.is_leader() is True
        clock.advance(5.0)  # ...now the grant has lapsed
        assert elector.is_leader() is False

    def test_check_fencing_rejects_deposed_leader(self):
        """The deposed-mid-cycle case: a's local deadline still holds,
        but the lease has moved on — the fencing re-read must refuse,
        and demote a on the spot."""
        fake = FakeKubeClient()
        clock = FakeClock()
        a = LeaseElector(fake, "a", lease_name="l", lease_duration_s=100.0,
                         clock=clock.now)
        b = LeaseElector(fake, "b", lease_name="l", lease_duration_s=100.0,
                         clock=clock.now)
        a.tick()
        assert a.check_fencing() is True
        # force-expire on the server only (a's local deadline is 100 s
        # out), then b takes over with token 2
        with fake._lock:
            fake._leases[("default", "l")]["spec"]["renewTime"] = -1e9
        assert b.tick() is True
        assert a.is_leader() is True  # locally still convinced...
        assert a.check_fencing() is False  # ...but the lease knows better
        assert a.is_leader() is False  # and the refusal demotes it

    def test_check_fencing_fails_safe_on_api_error(self):
        fake = FakeKubeClient()
        clock = FakeClock()
        plan = FaultPlan()
        elector = LeaseElector(fake, "a", lease_name="l", clock=clock.now)
        elector.tick()
        fake.fault_plan = plan
        fake.fault_clock = clock
        plan.outage("get_lease", status=503)
        assert elector.check_fencing() is False

    def test_renew_conflict_demotes(self):
        """A renew that answers 409 means a takeover already committed
        somewhere: the old leader must not keep acting on a stale
        token."""
        fake = FakeKubeClient()
        clock = FakeClock()
        a = LeaseElector(fake, "a", lease_name="l", lease_duration_s=10.0,
                         clock=clock.now)
        a.tick()
        # move the lease under a's feet (fresh RV, new holder)
        current = fake.get_lease("default", "l")
        current["spec"]["holderIdentity"] = "b"
        current["spec"]["leaseTransitions"] = 2
        fake.update_lease(current)
        # a's next tick observes the foreign holder and follows
        assert a.tick() is False
        assert a.fencing_token() is None

    def test_leader_gauge_and_transition_counter(self):
        fake = FakeKubeClient()
        clock = FakeClock()
        before = trace.COUNTERS.get("pas_leader_transitions_total")
        elector = LeaseElector(fake, "gauge-rep", lease_name="l",
                               lease_duration_s=10.0, clock=clock.now)
        elector.tick()
        assert trace.COUNTERS.get(
            "pas_leader", labels={"replica": "gauge-rep"}, kind="gauge"
        ) == 1
        assert trace.COUNTERS.get("pas_leader_transitions_total") == before + 1
        clock.advance(20.0)  # lapse without renew -> self-demotion
        assert elector.is_leader() is False
        assert trace.COUNTERS.get(
            "pas_leader", labels={"replica": "gauge-rep"}, kind="gauge"
        ) == 0
        assert trace.COUNTERS.get("pas_leader_transitions_total") == before + 2

    def test_lease_spec_uses_real_api_wire_types(self):
        """The real API server rejects float times / float durations:
        acquireTime/renewTime must be RFC3339 MicroTime strings and
        leaseDurationSeconds an int — and both directions round-trip
        through the parser (a lease written by kubectl/client-go, with
        or without fractional seconds, reads the same way)."""
        from platform_aware_scheduling_tpu.kube.lease import (
            format_micro_time,
            parse_lease_time,
        )

        fake = FakeKubeClient()
        clock = FakeClock()
        elector = LeaseElector(fake, "a", lease_name="l",
                               lease_duration_s=10.0, clock=clock.now)
        elector.tick()
        spec = fake.get_lease("default", "l")["spec"]
        assert isinstance(spec["leaseDurationSeconds"], int)
        assert isinstance(spec["acquireTime"], str)
        assert isinstance(spec["renewTime"], str)
        assert parse_lease_time(spec["renewTime"]) == pytest.approx(
            clock.now(), abs=1e-5
        )
        # round-trip + foreign spellings + garbage fails safe to 0
        assert parse_lease_time(format_micro_time(1234.5)) == pytest.approx(
            1234.5, abs=1e-5
        )
        assert parse_lease_time("2026-08-04T12:00:00Z") > 0
        assert parse_lease_time("2026-08-04T12:00:00.123456Z") > 0
        assert parse_lease_time(42) == 42.0
        assert parse_lease_time("not-a-time") == 0.0
        assert parse_lease_time(None) == 0.0
        # a foreign-written lease (string MicroTime) renews cleanly
        clock.advance(3.0)
        assert elector.tick() is True

    def test_status_payload(self):
        fake = FakeKubeClient()
        clock = FakeClock()
        elector = LeaseElector(fake, "a", lease_name="l", clock=clock.now)
        elector.tick()
        status = elector.status()
        assert status["role"] == "leader"
        assert status["fencing_token"] == 1
        assert status["lease"]["holder"] == "a"
        ok, reason = elector.readiness_condition()
        assert ok is True and "leader" in reason


class TestLeaseVerbRetryClassification:
    """Satellite: lease verbs are idempotent-by-fencing — they retry
    like reads under the policy (with their own pas_kube_retry_total
    verb labels), while 409 stays deterministic and un-retried."""

    def test_verb_classes(self):
        assert "get_lease" in READ_VERBS
        assert "get_configmap" in READ_VERBS
        assert FENCED_WRITE_VERBS == {"create_lease", "update_lease"}
        assert "create_configmap" in WRITE_VERBS
        assert "update_configmap" in WRITE_VERBS
        assert FENCED_WRITE_VERBS.isdisjoint(WRITE_VERBS)

    def test_update_lease_retries_on_deterministic_schedule(self):
        """Two scripted 503s then success: the exact jittered backoff
        schedule (seed ^ stable_hash(verb)) is slept, the retry counter
        moves under verb=update_lease, and the call commits."""
        fake = FakeKubeClient()
        clock = FakeClock()
        plan = FaultPlan()
        fake.fault_plan = plan
        fake.fault_clock = clock
        created = fake.create_lease(_lease())
        plan.fail("update_lease", 2, status=503)
        slept = []
        policy = RetryPolicy(
            max_attempts=4, base_delay_s=0.1, max_delay_s=5.0,
            deadline_s=30.0, seed=11,
        )
        client = FaultTolerantClient(
            fake,
            policy=policy,
            breakers=CircuitBreakerRegistry(clock=clock.now),
            clock=clock.now,
            sleep=lambda s: (slept.append(s), clock.advance(s)),
        )
        before = trace.COUNTERS.get(
            "pas_kube_retry_total",
            labels={"verb": "update_lease", "reason": "server_error"},
        )
        updated = client.update_lease(
            _lease(rv=created["metadata"]["resourceVersion"], holder="y")
        )
        assert updated["spec"]["holderIdentity"] == "y"
        expected = [
            backoff_delay(n, 0.1, 5.0, seed=11 ^ stable_hash("update_lease"))
            for n in (1, 2)
        ]
        assert slept == pytest.approx(expected)
        assert trace.COUNTERS.get(
            "pas_kube_retry_total",
            labels={"verb": "update_lease", "reason": "server_error"},
        ) == before + 2

    def test_conflict_is_never_retried(self):
        fake = FakeKubeClient()
        clock = FakeClock()
        plan = FaultPlan()
        fake.fault_plan = plan
        fake.fault_clock = clock
        fake.create_lease(_lease())
        slept = []
        client = FaultTolerantClient(
            fake,
            policy=RetryPolicy(max_attempts=4),
            breakers=CircuitBreakerRegistry(clock=clock.now),
            clock=clock.now,
            sleep=slept.append,
        )
        with pytest.raises(ConflictError):
            client.update_lease(_lease(rv="stale-rv"))
        assert slept == []  # deterministic answer: one attempt, no sleep
        assert plan.call_count("update_lease") == 1

    def test_elector_caps_lease_verb_deadlines_at_lease_duration(self):
        """A retry schedule outliving the lease is worthless: building
        an elector over the FT client tightens the lease verbs' retry
        deadline to the lease duration (operator-set LOWER deadlines
        stand; other verbs untouched)."""
        clock = FakeClock()
        policy = RetryPolicy(
            deadline_s=30.0, verb_deadlines={"create_lease": 2.0}
        )
        client = FaultTolerantClient(
            FakeKubeClient(),
            policy=policy,
            breakers=CircuitBreakerRegistry(clock=clock.now),
            clock=clock.now,
            sleep=clock.sleep,
        )
        LeaseElector(client, "a", lease_name="l", lease_duration_s=10.0,
                     clock=clock.now)
        assert policy.deadline_for("get_lease") == 10.0
        assert policy.deadline_for("update_lease") == 10.0
        assert policy.deadline_for("create_lease") == 2.0  # already tighter
        assert policy.deadline_for("list_nodes") == 30.0  # untouched

    def test_elector_rides_the_fault_tolerant_client(self):
        """A transient 503 on renew is absorbed by the retry layer: the
        elector's tick succeeds without ever observing the fault."""
        fake = FakeKubeClient()
        clock = FakeClock()
        plan = FaultPlan()
        fake.fault_plan = plan
        fake.fault_clock = clock
        client = FaultTolerantClient(
            fake,
            policy=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                               max_delay_s=0.1),
            breakers=CircuitBreakerRegistry(clock=clock.now),
            clock=clock.now,
            sleep=clock.sleep,
        )
        elector = LeaseElector(client, "a", lease_name="l",
                               lease_duration_s=10.0, clock=clock.now)
        elector.tick()
        plan.fail("update_lease", 1, status=503)
        clock.advance(3.0)
        assert elector.tick() is True  # retried through the blip
        assert elector.fencing_token() == 1


@pytest.mark.parametrize("serving", ["threaded", "async"])
class TestDebugLeaderEndpoint:
    def test_codes_and_payload(self, serving):
        from benchmarks.http_load import build_extender

        ext, _names = build_extender(8, device=True)
        server = (
            start_async(ext) if serving == "async" else start_threaded(ext)
        )
        try:
            # unwired: 404, but discoverable in the /debug index
            status, _h, _p = get_request(server.port, "/debug/leader")
            assert status == 404
            status, _h, payload = get_request(server.port, "/debug")
            assert status == 200
            paths = [e["path"] for e in json.loads(payload)["endpoints"]]
            assert "/debug/leader" in paths
            # wired: 200 + role/token; non-GET 405
            clock = FakeClock()
            elector = LeaseElector(
                FakeKubeClient(), "r0", lease_name="l", clock=clock.now
            )
            elector.tick()
            ext.leadership = elector
            status, _h, payload = get_request(server.port, "/debug/leader")
            assert status == 200
            snap = json.loads(payload)
            assert snap["role"] == "leader"
            assert snap["fencing_token"] == 1
            assert snap["lease"]["holder"] == "r0"
            status, _h, _p = raw_request(
                server.port, post_bytes("/debug/leader", b"{}")
            )
            assert status == 405
        finally:
            server.shutdown()

"""Golden wire fixtures (tests/golden/): the upstream kube-scheduler's
lowercase-tagged bodies and the reference's capitalized bodies must both
decode, produce identical responses through the native and Python paths,
and match the pinned response bytes exactly.

This suite exists because the reference only interoperates with the real
kube-scheduler via Go's case-insensitive unmarshal (its own structs are
untagged/capitalized while the scheduler marshals lowercase tags) — a
detail invisible to hermetic tests that always speak one spelling.
"""

import json
import os

import pytest

from platform_aware_scheduling_tpu.extender.server import HTTPRequest
from platform_aware_scheduling_tpu.extender.types import Args, BindingArgs
from platform_aware_scheduling_tpu.native import get_wirec
from platform_aware_scheduling_tpu.ops.state import TensorStateMirror
from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import NodeMetric
from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import TASPolicy
from platform_aware_scheduling_tpu.tas.telemetryscheduler import MetricsExtender
from platform_aware_scheduling_tpu.testing.builders import make_policy, rule
from platform_aware_scheduling_tpu.utils.quantity import Quantity

GOLDEN = os.path.join(os.path.dirname(os.path.abspath(__file__)), "golden")

# canned state documented in golden/README.md
VALUES = {"gw-a": 50, "gw-b": 90, "gw-c": 10, "gw-d": 70}

REQUESTS = {
    "upstream_nodes": "prioritize_request_upstream.json",
    "upstream_nodenames": "prioritize_request_upstream_nodenames.json",
    "reference_nodes": "prioritize_request_reference_style.json",
    "reference_nodenames": "prioritize_request_reference_style_nodenames.json",
}


def fixture(name: str) -> bytes:
    with open(os.path.join(GOLDEN, name), "rb") as f:
        return f.read()


def golden_extender():
    cache = AutoUpdatingCache()
    mirror = TensorStateMirror()
    mirror.attach(cache)
    cache.write_policy(
        "default",
        "golden-pol",
        TASPolicy.from_obj(
            make_policy(
                "golden-pol",
                strategies={
                    "scheduleonmetric": [
                        rule("golden_metric", "GreaterThan", 0)
                    ],
                    "dontschedule": [
                        rule("golden_metric", "GreaterThan", 80)
                    ],
                },
            )
        ),
    )
    cache.write_metric(
        "golden_metric",
        {n: NodeMetric(value=Quantity(v)) for n, v in VALUES.items()},
    )
    return MetricsExtender(cache, mirror=mirror, node_cache_capable=True)


def post(ext, verb: str, body: bytes):
    request = HTTPRequest(
        method="POST",
        path=f"/scheduler/{verb}",
        headers={"Content-Type": "application/json"},
        body=body,
    )
    return getattr(ext, verb if verb != "prioritize" else "prioritize")(
        request
    )


class TestGeneratorPinned:
    def test_fixtures_match_generator(self, tmp_path):
        """The committed fixtures are exactly what generate.py emits —
        edits must go through the generator so derivation stays recorded.
        Generation goes to a temp dir: writing in place would self-heal a
        drift on the second run (and dirty the tree on every run)."""
        import subprocess
        import sys

        subprocess.run(
            [
                sys.executable,
                os.path.join(GOLDEN, "generate.py"),
                str(tmp_path),
            ],
            check=True,
        )
        for name in REQUESTS.values():
            generated = (tmp_path / name).read_bytes()
            assert generated == fixture(name), f"{name} drifted from generator"


class TestRequestDecoding:
    @pytest.mark.parametrize("key", sorted(REQUESTS))
    def test_args_decode(self, key):
        args = Args.from_json(fixture(REQUESTS[key]))
        assert args.pod.name == "golden-pod"
        assert args.pod.namespace == "default"
        assert args.pod.get_labels()["telemetry-policy"] == "golden-pol"
        if key.endswith("_nodes"):
            assert [n.name for n in args.nodes] == sorted(VALUES)
        else:
            assert args.node_names == sorted(VALUES)

    def test_upstream_and_reference_decode_identically(self):
        up = Args.from_json(fixture(REQUESTS["upstream_nodenames"]))
        ref = Args.from_json(fixture(REQUESTS["reference_nodenames"]))
        assert up.node_names == ref.node_names
        assert up.pod.raw == ref.pod.raw

    def test_duplicate_object_key_replaces_wholesale_both_paths_agree(self):
        """{"Pod": {name+label}, "Pod": {name only}}: Go would MERGE
        per-field (keeping the label); this framework's documented
        envelope replaces the object wholesale — what is pinned here is
        that the native scanner and the Python fold AGREE (types.py
        module doc, 'Envelope note on duplicate keys')."""
        body = (
            b'{"Pod": {"metadata": {"name": "p", '
            b'"labels": {"telemetry-policy": "golden-pol"}}}, '
            b'"Pod": {"metadata": {"name": "q"}}, '
            b'"NodeNames": ["gw-a"]}'
        )
        args = Args.from_json(body)
        assert args.pod.name == "q"
        assert "telemetry-policy" not in args.pod.get_labels()
        wirec = get_wirec()
        if wirec is not None:
            parsed = wirec.parse_prioritize(body)
            assert parsed.pod_name == "q"
            assert parsed.policy_label is None

    def test_bind_null_case_variant_does_not_clobber_string(self):
        """{"Node":"n1","node":null}: Go assigns "n1" then ignores the
        null (null into a string field has no effect) — so must we."""
        args = BindingArgs.from_json(b'{"Node": "n1", "node": null}')
        assert args.node == "n1"

    def test_bind_args_upstream_tags(self):
        args = BindingArgs.from_json(fixture("bind_request_upstream.json"))
        assert args.pod_name == "golden-pod"
        assert args.pod_namespace == "default"
        assert args.pod_uid.startswith("8f2a7e6c")
        assert args.node == "gw-b"

    def test_mixed_case_last_wins_like_go(self):
        body = json.dumps(
            {
                "NodeNames": ["x"],
                "nodenames": ["gw-a", "gw-b"],
                "pod": {"metadata": {"name": "p"}},
            }
        ).encode()
        args = Args.from_json(body)
        assert args.node_names == ["gw-a", "gw-b"]

    def test_exact_duplicate_plus_case_variant_resolves_in_doc_order(self):
        """{"NodeNames":A, "nodenames":B, "NodeNames":C} -> C in Go (raw
        document order, last wins) even though json.loads collapses the
        exact duplicates at their first position; the native scanner
        scans raw bytes so it agrees with Go — the Python fold must too."""
        body = (
            b'{"NodeNames": ["x"], "nodenames": ["y"],'
            b' "NodeNames": ["gw-c"], "pod": {"metadata": {"name": "p"}}}'
        )
        args = Args.from_json(body)
        assert args.node_names == ["gw-c"]
        wirec = get_wirec()
        if wirec is not None:
            parsed = wirec.parse_prioritize(body)
            assert parsed.node_names_list() == ["gw-c"]

    @pytest.mark.skipif(get_wirec() is None, reason="no C toolchain")
    @pytest.mark.parametrize("key", sorted(REQUESTS))
    def test_native_scanner_decodes(self, key):
        parsed = get_wirec().parse_prioritize(fixture(REQUESTS[key]))
        assert parsed.pod_name == "golden-pod"
        assert parsed.policy_label == "golden-pol"
        if key.endswith("_nodes"):
            assert parsed.node_names() == sorted(VALUES)
        else:
            assert parsed.node_names_list() == sorted(VALUES)


class TestGoldenResponses:
    """Response bytes pinned against *.golden files (regenerate with
    --update after an intentional wire change: see __main__ below)."""

    CASES = [
        ("prioritize", "upstream_nodenames", "prioritize_nodenames_response.golden"),
        ("prioritize", "reference_nodenames", "prioritize_nodenames_response.golden"),
        ("prioritize", "upstream_nodes", "prioritize_nodes_response.golden"),
        ("prioritize", "reference_nodes", "prioritize_nodes_response.golden"),
        ("filter", "upstream_nodenames", "filter_nodenames_response.golden"),
        ("filter", "reference_nodenames", "filter_nodenames_response.golden"),
        ("filter", "upstream_nodes", "filter_nodes_response.golden"),
        ("filter", "reference_nodes", "filter_nodes_response.golden"),
    ]

    @pytest.mark.parametrize("verb,req,golden", CASES)
    def test_response_bytes_pinned(self, verb, req, golden, monkeypatch):
        for native in (False, True):
            if native and get_wirec() is None:
                continue
            if not native:
                monkeypatch.setenv("PAS_TPU_NO_NATIVE", "1")
            else:
                monkeypatch.delenv("PAS_TPU_NO_NATIVE", raising=False)
            ext = golden_extender()
            response = post(ext, verb, fixture(REQUESTS[req]))
            assert response.status == 200
            assert response.body == fixture(golden), (verb, req, native)

    def test_semantics_hand_checkable(self):
        """Scores are ordinal 10-rank over metric desc: gw-b(90) gw-d(70)
        gw-a(50) gw-c(10); filter rejects gw-b (90 > 80)."""
        prio = json.loads(fixture("prioritize_nodenames_response.golden"))
        assert [(e["Host"], e["Score"]) for e in prio] == [
            ("gw-b", 10), ("gw-d", 9), ("gw-a", 8), ("gw-c", 7),
        ]
        filt = json.loads(fixture("filter_nodenames_response.golden"))
        assert filt["NodeNames"] == ["gw-a", "gw-c", "gw-d"]
        assert filt["FailedNodes"] == {
            "gw-b": "policy golden-pol: metric golden_metric=90 > threshold 80"
        }
        legacy = json.loads(fixture("filter_nodes_response.golden"))
        # the Nodes branch echoes full node objects and keeps the
        # reference's trailing-"" NodeNames split quirk
        assert [n["metadata"]["name"] for n in legacy["Nodes"]["items"]] == [
            "gw-a", "gw-c", "gw-d",
        ]
        assert legacy["NodeNames"] == ["gw-a", "gw-c", "gw-d", ""]
        assert legacy["FailedNodes"] == {
            "gw-b": "policy golden-pol: metric golden_metric=90 > threshold 80"
        }


def update_goldens():
    """Regenerate the *.golden response files from the current (exact
    Python path) implementation."""
    os.environ["PAS_TPU_NO_NATIVE"] = "1"
    ext = golden_extender()
    outputs = {
        "prioritize_nodenames_response.golden": post(
            ext, "prioritize", fixture(REQUESTS["upstream_nodenames"])
        ),
        "prioritize_nodes_response.golden": post(
            ext, "prioritize", fixture(REQUESTS["upstream_nodes"])
        ),
        "filter_nodenames_response.golden": post(
            ext, "filter", fixture(REQUESTS["upstream_nodenames"])
        ),
        # legacy Nodes branch: full node echo + the trailing-"" NodeNames
        # split quirk (telemetryscheduler.go:212)
        "filter_nodes_response.golden": post(
            ext, "filter", fixture(REQUESTS["upstream_nodes"])
        ),
    }
    for name, response in outputs.items():
        assert response.status == 200, name
        with open(os.path.join(GOLDEN, name), "wb") as f:
            f.write(response.body)
        print(f"wrote {name} ({len(response.body)} bytes)")


if __name__ == "__main__":
    import sys

    if "--update" in sys.argv:
        update_goldens()
    else:
        print("usage: python tests/test_golden_wire.py --update")

"""Pin ops/binpack.py edge cases the gang/topology work leans on
(ISSUE 7 satellite): zero-card nodes, requests exactly equal to per-card
capacity, and int64 saturation near the quantization bound.  These pin
CURRENT behavior so the shared i64/masking machinery can be reused with
known semantics."""

import numpy as np
import pytest

import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.binpack import (
    BinpackNodeState,
    BinpackRequest,
    NO_CARD,
    binpack_kernel,
)

INT64_MAX = 2**63 - 1


def make_state(used, capacity, card_valid=None, card_real=None):
    """[1, C, R] single-node state from plain int lists."""
    used = np.asarray(used, dtype=np.int64)[None, :, :]  # [1, C, R]
    capacity = np.asarray(capacity, dtype=np.int64)[None, :]  # [1, R]
    n, c, r = used.shape
    used_hi, used_lo = i64.split_int64_np(used)
    cap_hi, cap_lo = i64.split_int64_np(capacity)
    valid = (
        np.ones((n, c), bool)
        if card_valid is None
        else np.asarray(card_valid, bool)[None, :]
    )
    real = (
        np.ones((n, c), bool)
        if card_real is None
        else np.asarray(card_real, bool)[None, :]
    )
    return BinpackNodeState(
        used=i64.I64(hi=jnp.asarray(used_hi), lo=jnp.asarray(used_lo)),
        capacity=i64.I64(hi=jnp.asarray(cap_hi), lo=jnp.asarray(cap_lo)),
        cap_present=jnp.ones((n, r), bool),
        card_valid=jnp.asarray(valid),
        card_real=jnp.asarray(real),
        card_order=jnp.broadcast_to(
            jnp.arange(c, dtype=jnp.int32), (n, c)
        ),
    )


def make_request(need, num_gpus=1):
    """[1, R] single-container request."""
    need = np.asarray(need, dtype=np.int64)[None, :]
    need_hi, need_lo = i64.split_int64_np(need)
    return BinpackRequest(
        need=i64.I64(hi=jnp.asarray(need_hi), lo=jnp.asarray(need_lo)),
        need_active=jnp.asarray(need != 0)
        if np.any(need)
        else jnp.ones_like(jnp.asarray(need), bool),
        num_gpus=jnp.asarray([num_gpus], dtype=jnp.int32),
        container_active=jnp.asarray([True]),
    )


class TestZeroCardNodes:
    def test_no_real_cards_fails_a_gpu_request(self):
        state = make_state(
            used=[[0], [0]], capacity=[100],
            card_real=[False, False],
        )
        result = binpack_kernel(state, make_request([10]), max_gpus=1)
        assert not bool(result.fits[0])
        assert int(result.cards[0, 0, 0]) == int(NO_CARD)

    def test_no_valid_cards_fails_a_gpu_request(self):
        """Cards gone from the node's GPU label (card_valid false) are
        just as unusable as padding lanes."""
        state = make_state(
            used=[[0], [0]], capacity=[100],
            card_valid=[False, False],
        )
        result = binpack_kernel(state, make_request([10]), max_gpus=1)
        assert not bool(result.fits[0])

    def test_zero_gpu_container_fits_a_cardless_node(self):
        """A container wanting zero GPUs books nothing and passes even
        with no cards at all (wanted = step < num_gpus never holds)."""
        state = make_state(
            used=[[0]], capacity=[100], card_real=[False],
        )
        result = binpack_kernel(
            state, make_request([10], num_gpus=0), max_gpus=1
        )
        assert bool(result.fits[0])


class TestExactCapacity:
    def test_request_exactly_equal_to_capacity_fits(self):
        """used + need == cap passes checkResourceCapacity (<=, not <)."""
        state = make_state(used=[[0]], capacity=[100])
        result = binpack_kernel(state, make_request([100]), max_gpus=1)
        assert bool(result.fits[0])
        assert int(result.cards[0, 0, 0]) == 0

    def test_one_unit_over_capacity_fails(self):
        state = make_state(used=[[1]], capacity=[100])
        result = binpack_kernel(state, make_request([100]), max_gpus=1)
        assert not bool(result.fits[0])

    def test_two_full_cap_shares_take_two_cards(self):
        """Each share fills a card exactly; first-fit walks to the next
        card in order rather than overflowing the first."""
        state = make_state(used=[[0], [0]], capacity=[100])
        result = binpack_kernel(
            state, make_request([100], num_gpus=2), max_gpus=2
        )
        assert bool(result.fits[0])
        picks = [int(result.cards[0, 0, k]) for k in range(2)]
        assert picks == [0, 1]


class TestI64Saturation:
    def test_sum_overflowing_int64_fails(self):
        """used + need past INT64_MAX must be detected as overflow (the
        split-limb sign-flip check), never wrap into a bogus fit."""
        state = make_state(used=[[INT64_MAX - 1]], capacity=[INT64_MAX])
        result = binpack_kernel(state, make_request([2]), max_gpus=1)
        assert not bool(result.fits[0])

    def test_sum_landing_exactly_on_int64_max_fits(self):
        state = make_state(used=[[INT64_MAX - 2]], capacity=[INT64_MAX])
        result = binpack_kernel(state, make_request([2]), max_gpus=1)
        assert bool(result.fits[0])

    def test_negative_need_fails(self):
        """A negative request share can never fit (need_neg gate)."""
        state = make_state(used=[[0]], capacity=[100])
        result = binpack_kernel(state, make_request([-1]), max_gpus=1)
        assert not bool(result.fits[0])

    def test_nonpositive_capacity_fails(self):
        """Capacity <= 0 fails cap_ok even for a zero-cost share."""
        state = make_state(used=[[0]], capacity=[0])
        result = binpack_kernel(state, make_request([1]), max_gpus=1)
        assert not bool(result.fits[0])


if __name__ == "__main__":
    raise SystemExit(pytest.main([__file__, "-q"]))

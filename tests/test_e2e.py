"""E2E tier: the full TAS service — HTTP server, cache, mirror, controller,
metric puller, enforcer — assembled exactly as cmd/tas.py does, driven over
a real socket, with cluster state in the fake kube layer.

Mirrors the reference's kind-cluster e2e scenarios
(reference .github/e2e/e2e_test.go):
  * TestTASFilter   (:89)  — only the node passing dontschedule survives
  * TestTASPrioritize (:126) — the best-metric node wins
  * TestTASDeschedule (:162) — violating nodes get the <policy>=violating label
  * TestAddAndDeletePolicy (:203) — 5x policy churn keeps answering correctly
The metric fixtures play the role of the node{1,2,3} textfile fixtures
(.github/scripts/policies/)."""

import json
import time
import urllib.request

import pytest

from platform_aware_scheduling_tpu.cmd.tas import assemble
from platform_aware_scheduling_tpu.extender.server import Server
from platform_aware_scheduling_tpu.tas.metrics import CustomMetricsClient
from platform_aware_scheduling_tpu.testing.builders import (
    make_node,
    make_policy,
    rule,
)
from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient

SYNC_PERIOD_S = 0.05


def wait_until(pred, timeout=30.0):  # generous: suite runs compile JAX concurrently
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


@pytest.fixture()
def cluster():
    kube = FakeKubeClient()
    for name in ("kind-worker", "kind-worker2", "kind-worker3"):
        kube.add_node(make_node(name))
    # textfile-fixture equivalents (.github/scripts/policies/node{1,2,3}):
    # only kind-worker2 passes filter1 <= 40; worker2 wins prioritize1;
    # worker2 violates deschedule1 > 8
    metrics = {
        "filter1_metric": {"kind-worker": 90, "kind-worker2": 20, "kind-worker3": 70},
        "prioritize1_metric": {"kind-worker": 10, "kind-worker2": 9999, "kind-worker3": 50},
        "deschedule1_metric": {"kind-worker": 1, "kind-worker2": 9, "kind-worker3": 2},
    }
    for metric, per_node in metrics.items():
        for node, value in per_node.items():
            kube.set_node_metric(metric, node, str(value))

    cache, mirror, extender, controller, enforcer, stop = assemble(
        kube, CustomMetricsClient(kube), SYNC_PERIOD_S
    )
    server = Server(extender)
    import threading

    threading.Thread(
        target=lambda: server.start_server(
            port="0", unsafe=True, host="127.0.0.1", block=True
        ),
        daemon=True,
    ).start()
    assert server.wait_ready()
    yield kube, cache, server, stop
    stop.set()
    server.shutdown()


def call(server, verb, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/scheduler/{verb}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        resp = urllib.request.urlopen(req, timeout=10)
        return resp.status, resp.read()
    except urllib.error.HTTPError as err:
        return err.code, err.read()


def sched_args(policy_name):
    return {
        "Pod": {
            "metadata": {
                "name": "demo-pod",
                "namespace": "default",
                "labels": {"telemetry-policy": policy_name},
            }
        },
        "Nodes": {
            "items": [
                {"metadata": {"name": n}}
                for n in ("kind-worker", "kind-worker2", "kind-worker3")
            ]
        },
    }


def demo_policy(name="e2e-policy"):
    return make_policy(
        name,
        strategies={
            "dontschedule": [rule("filter1_metric", "GreaterThan", 40)],
            "scheduleonmetric": [rule("prioritize1_metric", "GreaterThan", 0)],
            "deschedule": [rule("deschedule1_metric", "GreaterThan", 8)],
        },
    )


def policy_ready(kube, server, name):
    """Policy created AND metrics pulled (the waitForMetrics equivalent,
    e2e_test.go:242-255): a filter answer that actually excludes nodes."""

    def check():
        status, body = call(server, "filter", sched_args(name))
        if status != 200:
            return False
        out = json.loads(body)
        return out.get("FailedNodes")

    return wait_until(check)


class TestE2E:
    def test_filter(self, cluster):
        kube, cache, server, _ = cluster
        kube.create_taspolicy(demo_policy())
        assert policy_ready(kube, server, "e2e-policy")
        status, body = call(server, "filter", sched_args("e2e-policy"))
        assert status == 200
        out = json.loads(body)
        assert out["NodeNames"] == ["kind-worker2", ""]
        assert set(out["FailedNodes"]) == {"kind-worker", "kind-worker3"}

    def test_prioritize(self, cluster):
        kube, cache, server, _ = cluster
        kube.create_taspolicy(demo_policy())
        assert policy_ready(kube, server, "e2e-policy")
        # policy_ready proves the FILTER metric is pulled; the
        # scheduleonmetric rule uses a different metric that can land a
        # refresh tick later — wait for a non-empty answer like the
        # reference's waitForMetrics does before asserting contents
        assert wait_until(
            lambda: json.loads(
                call(server, "prioritize", sched_args("e2e-policy"))[1]
            )
        )
        status, body = call(server, "prioritize", sched_args("e2e-policy"))
        assert status == 200
        out = json.loads(body)
        assert out[0] == {"Host": "kind-worker2", "Score": 10}
        assert len(out) == 3

    def test_deschedule_labels_node(self, cluster):
        kube, cache, server, _ = cluster
        kube.create_taspolicy(demo_policy())
        assert policy_ready(kube, server, "e2e-policy")
        # enforcer ticks every SYNC_PERIOD_S; kind-worker2 violates (9 > 8)
        assert wait_until(
            lambda: kube.get_node("kind-worker2").get_labels().get("e2e-policy")
            == "violating"
        )
        others = [
            kube.get_node(n).get_labels().get("e2e-policy")
            for n in ("kind-worker", "kind-worker3")
        ]
        assert all(v in (None, "null") for v in others)

    def test_deschedule_label_clears_when_healthy(self, cluster):
        kube, cache, server, _ = cluster
        kube.create_taspolicy(demo_policy())
        assert wait_until(
            lambda: kube.get_node("kind-worker2").get_labels().get("e2e-policy")
            == "violating"
        )
        kube.set_node_metric("deschedule1_metric", "kind-worker2", "1")
        # reference's label-to-"null" oddity (deschedule/enforce.go:118-132)
        assert wait_until(
            lambda: kube.get_node("kind-worker2").get_labels().get("e2e-policy")
            == "null"
        )

    def test_add_and_delete_policy_churn(self, cluster):
        """e2e_test.go:203-205: repeated add/delete must not wedge state."""
        kube, cache, server, _ = cluster
        for round_ in range(5):
            kube.create_taspolicy(demo_policy())
            assert policy_ready(kube, server, "e2e-policy"), round_
            status, body = call(server, "filter", sched_args("e2e-policy"))
            assert json.loads(body)["NodeNames"] == ["kind-worker2", ""], round_
            kube.delete_taspolicy("default", "e2e-policy")
            assert wait_until(
                lambda: json.loads(
                    call(server, "filter", sched_args("e2e-policy"))[1]
                )
                is None,
                timeout=5.0,
            ), round_

    def test_unknown_policy_404(self, cluster):
        _, _, server, _ = cluster
        status, body = call(server, "filter", sched_args("ghost-policy"))
        assert status == 404
        assert body == b"null\n"

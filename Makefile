# Build/test fan-out (capability parity: reference top-level Makefile:1-9).
.PHONY: all test e2e bench lint image clean dryrun

all: test

test:
	python -m pytest tests/ -q

e2e:
	python -m pytest tests/test_e2e.py -q

bench:
	python bench.py

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

lint:
	python -m compileall -q platform_aware_scheduling_tpu tests bench.py __graft_entry__.py

image:
	docker build -f deploy/images/Dockerfile.tas -t pas-tpu-tas .
	docker build -f deploy/images/Dockerfile.gas -t pas-tpu-gas .

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
	rm -rf build dist *.egg-info

# Build/test fan-out (capability parity: reference top-level Makefile:1-9).
.PHONY: all test e2e e2e-kind bench bench-http bench-gas bench-gang bench-configs bench-serving bench-rebalance bench-chaos bench-decisions bench-forecast bench-ha bench-twin bench-shard test-serving test-obs test-rebalance test-faults test-decisions test-gang test-forecast test-ha test-slo test-shard test-record test-control test-admission test-explain test-solveobs bench-control bench-admission bench-replay bench-ledger test-fuzz fuzz-smoke test-wirec trace-lint pascheck obs-smoke lint image clean dryrun

all: test

test:
	python -m pytest tests/ -q

e2e:
	python -m pytest tests/test_e2e.py -q

# real-cluster e2e (requires kind/helm/kubectl/docker; CI runs this);
# teardown always runs — a failure anywhere in setup OR the scenarios
# must not leak the kind cluster
e2e-kind:
	( bash .github/scripts/e2e_setup_cluster.sh && \
		python .github/e2e/run_e2e.py ); rc=$$?; \
		bash .github/scripts/e2e_teardown_cluster.sh; exit $$rc

bench:
	python bench.py

# north-star serving A/B alone (faster than the full bench)
bench-http:
	python -m benchmarks.http_load

# GAS wire A/B alone
bench-gas:
	python -m benchmarks.gas_load

# serving front-end head-to-head: threaded vs async c=1 -> c=8 scaling
# curve (docs/serving.md)
bench-serving:
	python -m benchmarks.http_load --scaling

# hermetic serving-subsystem suite (wire parity, coalescing,
# backpressure, the c=8 <= 3x c=1 bar) — CI runs this as its own step
test-serving:
	python -m pytest tests/test_serving.py -q

# closed-loop rebalancer suite (docs/rebalance.md): hysteresis, dry-run
# plan parity, actuation guards, active-vs-label-only convergence
test-rebalance:
	python -m pytest tests/test_rebalance.py -q

# rebalance convergence A/B alone: synthetic churn, active vs label-only
bench-rebalance:
	python -m benchmarks.rebalance_load

# fault-tolerance & chaos suite (docs/robustness.md): retry/backoff
# schedules, circuit transitions, degraded modes, and the end-to-end
# outage -> degrade -> recover -> resume invariant (zero evictions on
# stale data) — deterministic: fault plans + fake clocks, no real sleeps
test-faults:
	python -m pytest tests/test_faults.py -q

# chaos A/B alone: availability + p99 through the live front-end under a
# scripted 10% metrics-API error rate vs a clean baseline
bench-chaos:
	python -m benchmarks.chaos_load

# decision-provenance suite (docs/observability.md "Decision
# provenance"): reason-code parity host<->device, concrete FailedNodes
# reasons, ring bounds, /debug/decisions filtering, bind feedback
test-decisions:
	python -m pytest tests/test_decisions.py -q

# decision-log on-vs-off serving p99 A/B + placement-quality scrape
bench-decisions:
	python -m benchmarks.http_load --decisions

# gang & topology-aware scheduling suite (docs/gang.md): topology-kernel
# device<->host parity, reservation lifecycle + TTL, the all-or-nothing
# invariant over real sockets on both front-ends, gang-atomic eviction
test-gang:
	python -m pytest tests/test_gang.py tests/test_binpack_edges.py -q

# gang A/B alone: competing gangs (gang-on admits both, gang-off
# deadlocks half-placed) + 10k-node reservation throughput
bench-gang:
	python -m benchmarks.gang_load

# predictive-telemetry suite (docs/forecast.md): kernel device<->host
# byte-exact parity, history-ring semantics, forecast-vs-snapshot ranking
# parity through the real verbs on both front-ends, trend-aware
# hysteresis, degraded bounded extrapolation, /debug/forecast
test-forecast:
	python -m pytest tests/test_forecast.py -q

# forecast A/B alone: trending violated-at-bind + transient-spike
# eviction suppression + forecaster on-vs-off p99 (skip the 10k-node
# overhead tier with the scenario functions directly)
bench-forecast:
	python -m benchmarks.forecast_load

# HA control-plane suite (docs/robustness.md "HA & leader election"):
# lease conflict semantics, elector lifecycle + fencing, the multi-
# replica exactly-one-actuator invariant, crash-safe gang recovery
test-ha:
	python -m pytest tests/test_lease.py tests/test_ha.py -q

# HA A/B alone: c=8 spread over 3 replicas vs 1 + leader-kill failover
bench-ha:
	python -m benchmarks.ha_load

# SLO engine + digital-twin suite (docs/observability.md "SLOs & error
# budgets"): burn-rate window math on fake clocks, bucket quantile
# interpolation, /debug/slo + off-path pins, and the scenario matrix
# incl. the metric-storm page -> recover acceptance over real sockets
test-slo:
	python -m pytest tests/test_slo.py tests/test_twin.py -q -m 'not slow'

# digital-twin scenario matrix alone: every default scenario at 10k
# nodes, verdicts = the SLO engine's judgment (testing/twin.py)
bench-twin:
	python -m benchmarks.twin_load

# partition plane suite (docs/sharding.md): partition math +
# rendezvous determinism, journaled/fenced ownership incl. heartbeat
# renewal and lost write races, digest build/fencing/staleness, the
# scatter/gather plane, /debug/shard wire codes on both front-ends,
# off-path byte-identity, and the partitioned HA harness
test-shard:
	python -m pytest tests/test_shard.py -q -m 'not slow'

# sharded scale-out A/B alone: 4 partition-owner subprocesses vs one
# full-world replica — aggregate Filter rps and the measured ~1/P
# per-replica refresh cut (benchmarks/shard_load.py); exits nonzero
# unless both halves of the bet hold
bench-shard:
	python -m benchmarks.shard_load

# flight recorder + trace replay + what-if suite (docs/observability.md
# "Flight recorder & what-if"): anonymization sweep over real sockets,
# /debug/record + /debug/whatif codes, off-path byte-identity, the
# record->export->parse->replay round trip, and the hermetic overhead pin
test-record:
	python -m pytest tests/test_record.py -q -m 'not slow'

# budget feedback control suite (docs/observability.md "Budget feedback
# control"): knob ladders/clamps/rate limit, hysteresis + trend pre-arm,
# --sloControl fail-fast, /debug/control codes on both front-ends,
# off-path byte-identity, and the static-vs-self-tuning head-to-heads
test-control:
	python -m pytest tests/test_control.py -q -m 'not slow'

# the controller's head-to-head A/B alone: final error-budget ledgers
# static vs self-tuning on both programs + the quiet-day null
# (benchmarks/control_load.py); exits nonzero unless strictly better
bench-control:
	python -m benchmarks.control_load

# priority-aware admission plane suite (docs/admission.md): class
# ladder + bounded queue semantics, backfill/fairness, gang-atomic
# preemption with fenced-refusal containment, flag fail-fast,
# /debug/admission + off-path byte-identity, torus parity, and the
# acceptance scenarios over real sockets on both front-ends
test-admission:
	python -m pytest tests/test_admission.py -q -m 'not slow'

# causal event spine + /debug/explain suite (docs/observability.md
# "Explain plane"): journal bounds/ordering under writer torture,
# one-hop correlation walks, the /debug/explain wire contract on both
# front-ends, TraceBuffer top-K under concurrent completions
test-explain:
	python -m pytest tests/test_explain.py -q

# solve observatory suite (docs/observability.md "Solve observatory"):
# per-stage attribution sums to the measured total, churn edge cases
# (first pass, delete, byte-identical refresh), /debug/solve codes on
# both front-ends, off-path byte-identity, the recompile-watch twin
# gate, and the perf-ledger anchor round trip
test-solveobs:
	python -m pytest tests/test_solveobs.py -q -m 'not slow'

# perf-regression ledger: fresh per-stage solve floors + warm-verb
# floor vs the COMMITTED anchor (benchmarks/perf_anchor.json), plus the
# observatory instrumented-vs-off pin.  Report-only (shared runners
# jitter); add --strict to gate, --write to re-anchor after an
# intentional perf change (benchmarks/perf_ledger.py)
bench-ledger:
	python -m benchmarks.perf_ledger

# the admission plane's head-to-head alone: preemption cascade ON vs
# OFF through the real verbs + the quiet-diurnal null + gate overhead
# (benchmarks/admission_load.py); exits nonzero unless ON is strictly
# better and the quiet day stays silent
bench-admission:
	python -m benchmarks.admission_load

# adversarial scenario search suite (docs/robustness.md "Adversarial
# scenario search"): seeded-LCG determinism + the pinned draw values,
# genome generation/mutation/validation, byte-identical candidate
# replay, coverage-novelty corpus, delta-debug minimization, planted
# bugs, the oracle no-false-positive matrix, and the committed
# minimized scenarios under tests/scenarios/
test-fuzz:
	python -m pytest tests/test_fuzz.py tests/test_oracles.py -q

# coverage-guided fuzzing smoke (benchmarks/fuzz_load.py): the four CI
# gates inside one wall-clock budget — reproducibility (same seed =>
# byte-identical candidate sequence), planted-bug detection (the
# stale-digest splice must be found AND minimized to <= 20 ticks /
# <= 8 events), no false positives on the healthy tree, and the
# candidate-throughput floor; exits nonzero on any gate failure.  Any
# find on the healthy tree is a real bug and is printed, never swallowed
fuzz-smoke:
	env JAX_PLATFORMS=cpu python -m benchmarks.fuzz_load

# replay throughput (legacy vs vectorized twin load model) + the
# what-if demo: 2x load must degrade the availability verdict a 1x
# replay keeps green (testing/replay.py)
bench-replay:
	python -c "import json; from benchmarks.twin_load import replay_report; print(json.dumps(replay_report(), indent=2))"

# native wire-path sanitizer gate (docs/architecture.md "The wire
# path"): compile _wirec with -fsanitize=address,undefined and run the
# wire-path suites — scanner strictness, universe interning/refcounts,
# the differential fuzzer — against the instrumented artifact via the
# PAS_TPU_WIREC_SO loader hook.  libstdc++ rides LD_PRELOAD next to
# libasan so XLA's C++ exceptions resolve real___cxa_throw before the
# interceptor asserts on it; leak detection stays off (CPython itself
# "leaks" interned state at exit) — ASan still reports heap overflows,
# use-after-free, and double-free, UBSan everything undefined.
WIREC_SAN_SO := $(abspath build/_wirec_sanitized.so)
test-wirec:
	mkdir -p build
	$(CC) -O1 -g -fsanitize=address,undefined -fno-sanitize-recover=all \
		-Wall -Wextra -Wshadow -Wvla -Werror \
		-shared -fPIC \
		-I$$(python -c 'import sysconfig; print(sysconfig.get_paths()["include"])') \
		platform_aware_scheduling_tpu/native/wirec.c -o $(WIREC_SAN_SO)
	env LD_PRELOAD="$$($(CC) -print-file-name=libasan.so) $$($(CC) -print-file-name=libstdc++.so)" \
		ASAN_OPTIONS=detect_leaks=0:halt_on_error=1 \
		PAS_TPU_WIREC_SO=$(WIREC_SAN_SO) \
		python -m pytest tests/test_wirec.py tests/test_wire_universe.py \
		tests/test_wire_fuzz.py -q

# metric-name convention gate (docs/observability.md): every emitted
# metric is declared in trace.METRICS, pas_-prefixed snake_case, no
# duplicates, and live /metrics output parses as valid exposition
trace-lint:
	python -m pytest tests/test_trace_lint.py -q

# project-native static analysis (docs/analysis.md): clock discipline,
# hot-path blocking, lock scope/ordering, metric declaration cross-check;
# exits nonzero on any finding not pragma'd or baselined
pascheck:
	python -m platform_aware_scheduling_tpu.analysis

# control-plane & device observability suite: /healthz + /readyz
# condition toggling on both front-ends, workqueue/informer
# instrumentation, device watermarks / cost analysis / profile capture
test-obs:
	python -m pytest tests/test_health.py tests/test_kube_instrumentation.py \
		tests/test_devicewatch.py -q

# one-command deployment sanity check: boot both front-ends and curl
# /healthz, /readyz, /metrics, /debug/traces (docs/observability.md)
obs-smoke:
	python -m benchmarks.obs_smoke

# BASELINE configs #2/#3/#4/#5 + solver surface + mesh checks alone
bench-configs:
	python -m benchmarks.configs

dryrun:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		python -c "import __graft_entry__; __graft_entry__.dryrun_multichip(8)"

lint:
	python -m compileall -q platform_aware_scheduling_tpu tests bench.py __graft_entry__.py

image:
	docker build -f deploy/images/Dockerfile.tas -t pas-tpu-tas .
	docker build -f deploy/images/Dockerfile.gas -t pas-tpu-gas .

clean:
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
	rm -rf build dist *.egg-info

"""Int64 resource vectors with overflow-safe transactional arithmetic.

Reference: gpu-aware-scheduling/pkg/gpuscheduler/resource_map.go.  Semantics
reproduced exactly: ``add`` rejects negative inputs and detects int64
overflow (:77-98); ``subtract`` clamps at zero with a warning and errors on
missing keys (:103-127); ``divide`` floor-divides every entry (:129-145);
``add_rm``/``subtract_rm`` are transactional — they mutate only if every key
succeeds on a scratch copy (:38-73).

Python ints are unbounded, so int64 overflow is checked explicitly against
INT64_MAX — values beyond it must fail exactly like the Go wraparound check.
"""

from __future__ import annotations

from typing import Dict, Iterable

from platform_aware_scheduling_tpu.utils import klog

INT64_MAX = 2**63 - 1
INT64_MIN = -(2**63)


class ResourceMapError(ValueError):
    pass


class OverflowError64(ResourceMapError):
    """integer overflow (reference resource_map.go:15)"""


class InputError(ResourceMapError):
    """input error (reference resource_map.go:16)"""


class ResourceMap(Dict[str, int]):
    """name -> amount (reference resource_map.go:20)."""

    def new_copy(self) -> "ResourceMap":
        return ResourceMap(self)

    def copy_from(self, src: "ResourceMap") -> None:
        self.update(src)

    def add(self, key: str, value: int) -> None:
        """Add one resource amount; negative input or int64 overflow raise
        without mutating (resource_map.go:77-98)."""
        if value < 0:
            klog.error("bad input for add, key: %s", key)
            raise InputError("input error")
        if key in self:
            value += self[key]
            # the Go check is post-wraparound (value < 0); with unbounded
            # ints the equivalent is exceeding the int64 range
            if value > INT64_MAX:
                klog.error("overflow during add, key: %s", key)
                raise OverflowError64("integer overflow")
        self[key] = value

    def subtract(self, key: str, value: int) -> None:
        """Subtract one resource amount; clamps at zero, errors on missing
        key or negative input (resource_map.go:103-127)."""
        if value < 0:
            klog.error("bad input for subtract, key: %s", key)
            raise InputError("input error")
        if key not in self:
            klog.error("subtract attempted with non-existing key: %s", key)
            raise InputError("input error")
        result = self[key] - value
        if result < 0:
            klog.warning(
                "resource value for %s ended negative, capped to zero", key
            )
            result = 0
        self[key] = result

    def add_rm(self, src: "ResourceMap") -> None:
        """All-or-nothing add of another map (resource_map.go:38-53)."""
        scratch = self.new_copy()
        for key, value in src.items():
            scratch.add(key, value)
        self.copy_from(scratch)

    def subtract_rm(self, src: "ResourceMap") -> None:
        """All-or-nothing subtract of another map (resource_map.go:58-73)."""
        scratch = self.new_copy()
        for key, value in src.items():
            scratch.subtract(key, value)
        self.copy_from(scratch)

    def divide(self, divider: int) -> None:
        """Floor-divide every entry (resource_map.go:129-145)."""
        if divider < 1:
            klog.error("bad divider")
            raise InputError("input error")
        if divider == 1:
            return
        for key in self:
            v = self[key]
            # Go division truncates toward zero; // floors — differs on
            # negatives, which can't normally occur but cost nothing to match
            self[key] = -((-v) // divider) if v < 0 else v // divider


NodeResources = Dict[str, ResourceMap]  # card name -> used resources


def deep_copy_node_resources(src: NodeResources) -> NodeResources:
    return {card: rm.new_copy() for card, rm in src.items()}

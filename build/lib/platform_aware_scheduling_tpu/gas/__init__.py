"""GPU-aware scheduling (GAS): per-card resource bookkeeping and first-fit
bin-packing so fractional-GPU pods land on nodes where each individual card
can satisfy them (reference gpu-aware-scheduling/README.md:14-19).

Host layer mirrors the reference's semantics exactly; the batched filter
path runs ops/binpack.py — one vmapped XLA pass over every candidate node
instead of the reference's per-node loop under a global lock."""

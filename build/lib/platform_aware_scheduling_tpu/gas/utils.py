"""GAS pod/resource helpers.

Reference: gpu-aware-scheduling/pkg/gpuscheduler/utils.go and the constants
of scheduler.go:24-36.
"""

from __future__ import annotations

from typing import List

from platform_aware_scheduling_tpu.gas.resource_map import ResourceMap
from platform_aware_scheduling_tpu.kube.objects import Pod
from platform_aware_scheduling_tpu.utils.quantity import Quantity, QuantityParseError

RESOURCE_PREFIX = "gpu.intel.com/"  # utils.go:10-12
GPU_LIST_LABEL = "gpu.intel.com/cards"  # scheduler.go:29
GPU_PLUGIN_RESOURCE = "gpu.intel.com/i915"  # scheduler.go:30
TS_ANNOTATION = "gas-ts"  # scheduler.go:25
CARD_ANNOTATION = "gas-container-cards"  # scheduler.go:26


def _as_int64(raw) -> int:
    """Quantity string -> int64 via AsInt64 semantics: non-integer or
    out-of-range values read as 0 (the reference ignores the ok flag,
    utils.go:23-24)."""
    try:
        value, _ok = Quantity(str(raw)).as_int64()
    except QuantityParseError:
        return 0
    return value


def container_requests(pod: Pod) -> List[ResourceMap]:
    """One ResourceMap per container, holding only ``gpu.intel.com/*``
    requests (utils.go:14-32)."""
    all_resources: List[ResourceMap] = []
    for container in pod.containers:
        rm = ResourceMap()
        requests = (container.get("resources") or {}).get("requests") or {}
        for name, raw in requests.items():
            if name.startswith(RESOURCE_PREFIX):
                rm[name] = _as_int64(raw)
        all_resources.append(rm)
    return all_resources


def has_gpu_resources(pod) -> bool:
    """True if any container requests a ``gpu.intel.com/*`` resource
    (utils.go:34-50)."""
    if pod is None:
        return False
    for container in pod.containers:
        requests = (container.get("resources") or {}).get("requests") or {}
        for name in requests:
            if name.startswith(RESOURCE_PREFIX):
                return True
    return False


def is_completed_pod(pod: Pod) -> bool:
    """Deleted, Failed, or Succeeded pods are 'completed' and release their
    card resources (utils.go:52-71)."""
    if pod.deletion_timestamp is not None:
        return True
    return pod.phase in ("Failed", "Succeeded")

"""Exact int64 arithmetic/ordering on TPU via (hi: int32, lo: uint32) pairs.

Why: rule evaluation in the reference compares ``resource.Quantity`` values
against int64 targets with exact integer semantics
(reference pkg/strategies/core/operator.go:13-26 via ``Quantity.CmpInt64``).
Metric values in milli-units span the full int64 range (byte-valued memory
metrics overflow int32), but TPUs have no fast native s64 — XLA emulates it.
Instead we keep the split representation explicit: a 64-bit value ``v`` is
``(hi, lo)`` with ``hi = v >> 32`` (arithmetic, signed) and
``lo = v & 0xffffffff`` (unsigned).  Ordering of ``v`` equals lexicographic
ordering of ``(hi signed, lo unsigned)``, which maps directly onto
``lax.sort`` multi-key sorting and pairwise compares — all in fast 32-bit
TPU ops.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

INT64_MIN = -(2**63)
INT64_MAX = 2**63 - 1


class I64(NamedTuple):
    """A tensor of int64 values in split (hi, lo) form.  A pytree, so it
    passes transparently through jit/vmap/shard_map."""

    hi: jax.Array  # int32
    lo: jax.Array  # uint32

    @property
    def shape(self):
        return self.hi.shape


def from_int64(values: Union[np.ndarray, Sequence[int], int]) -> I64:
    """Host-side: numpy int64 array -> split representation."""
    arr = np.asarray(values, dtype=np.int64)
    hi = (arr >> np.int64(32)).astype(np.int32)
    lo = (arr & np.int64(0xFFFFFFFF)).astype(np.uint32)
    return I64(hi=jnp.asarray(hi), lo=jnp.asarray(lo))


def split_int64_np(values: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Numpy-only split (for host mirrors that stage into device buffers)."""
    arr = np.asarray(values, dtype=np.int64)
    return (arr >> np.int64(32)).astype(np.int32), (
        arr & np.int64(0xFFFFFFFF)
    ).astype(np.uint32)


def to_int64_np(value: I64) -> np.ndarray:
    """Device -> host: reassemble numpy int64 (for wire encoding/tests)."""
    hi = np.asarray(value.hi).astype(np.int64)
    lo = np.asarray(value.lo).astype(np.int64)
    return (hi << np.int64(32)) | lo


def full_like(template: I64, value: int) -> I64:
    hi = np.int32(np.int64(value) >> np.int64(32))
    lo = np.uint32(np.int64(value) & np.int64(0xFFFFFFFF))
    return I64(
        hi=jnp.full_like(template.hi, hi), lo=jnp.full_like(template.lo, lo)
    )


def cmp(a: I64, b: I64) -> jax.Array:
    """Elementwise sign(a - b) in {-1, 0, 1} as int32 — the device analog of
    ``Quantity.CmpInt64`` (reference operator.go:13-26)."""
    hi_lt = a.hi < b.hi
    hi_gt = a.hi > b.hi
    lo_lt = a.lo < b.lo  # unsigned compare
    lo_gt = a.lo > b.lo
    lt = hi_lt | (~hi_gt & lo_lt)
    gt = hi_gt | (~hi_lt & lo_gt)
    return jnp.where(lt, jnp.int32(-1), jnp.where(gt, jnp.int32(1), jnp.int32(0)))


def lt(a: I64, b: I64) -> jax.Array:
    return cmp(a, b) == -1


def eq(a: I64, b: I64) -> jax.Array:
    return (a.hi == b.hi) & (a.lo == b.lo)


def flip(a: I64) -> I64:
    """Bitwise complement: an order-*reversing* bijection on int64, so an
    ascending sort of ``flip(x)`` is a descending sort of ``x`` (used for
    the GreaterThan branch of OrderedList, reference operator.go:33-35)."""
    return I64(hi=~a.hi, lo=~a.lo)


def select(pred: jax.Array, on_true: I64, on_false: I64) -> I64:
    return I64(
        hi=jnp.where(pred, on_true.hi, on_false.hi),
        lo=jnp.where(pred, on_true.lo, on_false.lo),
    )


def add(a: I64, b: I64) -> I64:
    """Wrapping 64-bit add built from 32-bit limbs (carry via unsigned
    overflow detection)."""
    lo_sum = a.lo + b.lo
    carry = (lo_sum < a.lo).astype(jnp.int32)
    hi_sum = a.hi + b.hi + carry
    return I64(hi=hi_sum, lo=lo_sum)


def neg(a: I64) -> I64:
    """Two's-complement negate: ~a + 1."""
    lo = (~a.lo) + jnp.uint32(1)
    carry = (lo == 0).astype(jnp.int32)
    return I64(hi=(~a.hi) + carry, lo=lo)


def sub(a: I64, b: I64) -> I64:
    return add(a, neg(b))


def sort_by_key(
    key: I64, *values: jax.Array, tiebreak: jax.Array = None
) -> Tuple[jax.Array, ...]:
    """Sort ``values`` ascending by exact int64 ``key`` using lexicographic
    multi-key ``lax.sort`` over the 32-bit limbs.  ``tiebreak`` (int32) is an
    optional third key making the order total/deterministic (the reference's
    Go ``sort.Slice`` is unstable; we fix ties by node index)."""
    operands = [key.hi, key.lo]
    num_keys = 2
    if tiebreak is not None:
        operands.append(tiebreak)
        num_keys = 3
    operands.extend(values)
    out = jax.lax.sort(tuple(operands), num_keys=num_keys, dimension=-1)
    return out[num_keys:] if values else out

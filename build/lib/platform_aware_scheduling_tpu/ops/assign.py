"""Batched pods x nodes assignment solve.

The stock kube-scheduler schedules one pod at a time, paying one extender
round-trip per pod (SURVEY §3.2: the quadratic-in-practice loop).  This
module solves the whole pending set in one XLA program: greedy assignment
in pod-priority order with per-node capacity constraints, with exact int64
score keys.  The per-pod HTTP verbs can then be answered from the
precomputed solution (SURVEY §7 step 4).

Greedy-in-order matches what the sequential kube-scheduler+extender system
would produce: pod i gets its best feasible node given pods 0..i-1's
placements — so the batch solve is semantics-preserving, just ~P times
fewer round trips.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp

from platform_aware_scheduling_tpu.ops import i64

UNASSIGNED = jnp.int32(-1)


class AssignResult(NamedTuple):
    node_for_pod: jax.Array  # int32 [P] — node index or -1
    capacity_left: jax.Array  # int32 [N]


def lex_argmin(key: i64.I64, valid: jax.Array) -> tuple:
    """Index of the smallest key among valid lanes, ties to the lowest
    index; returns (idx, found).  Three cheap reductions instead of a sort."""
    big_hi = jnp.int32(2**31 - 1)
    big_lo = jnp.uint32(2**32 - 1)
    hi = jnp.where(valid, key.hi, big_hi)
    m_hi = jnp.min(hi)
    on_hi = valid & (key.hi == m_hi)
    lo = jnp.where(on_hi, key.lo, big_lo)
    m_lo = jnp.min(lo)
    on_lo = on_hi & (key.lo == m_lo)
    n = key.hi.shape[-1]
    idx = jnp.min(jnp.where(on_lo, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)))
    found = jnp.any(valid)
    return jnp.where(found, idx, UNASSIGNED), found


@partial(jax.jit, donate_argnums=())
def greedy_assign_kernel(
    score: i64.I64,  # [P, N] — larger is better
    eligible: jax.Array,  # bool [P, N] — pod may land on node (post-filter)
    capacity: jax.Array,  # int32 [N] — pods each node can still take
) -> AssignResult:
    """Assign every pending pod its best feasible node, in order."""

    def step(cap, pod):
        s_hi, s_lo, elig = pod
        ok = elig & (cap > 0)
        # maximize score == minimize flipped score
        flipped = i64.flip(i64.I64(hi=s_hi, lo=s_lo))
        best, found = lex_argmin(flipped, ok)
        take = jnp.where(
            found,
            jax.nn.one_hot(best, cap.shape[0], dtype=cap.dtype),
            jnp.zeros_like(cap),
        )
        return cap - take, best

    capacity_left, node_for_pod = jax.lax.scan(
        step, capacity, (score.hi, score.lo, eligible)
    )
    return AssignResult(node_for_pod=node_for_pod, capacity_left=capacity_left)


def _row_lex_argmax(score: i64.I64, ok: jax.Array) -> jax.Array:
    """Per-row argmax of exact-i64 scores over masked lanes, ties to the
    lowest index; -1 where no lane is ok.  [P, N] -> [P]."""
    neg_hi = jnp.int32(-(2**31))
    hi = jnp.where(ok, score.hi, neg_hi)
    m_hi = jnp.max(hi, axis=-1, keepdims=True)
    on_hi = ok & (score.hi == m_hi)
    lo = jnp.where(on_hi, score.lo, jnp.uint32(0))
    m_lo = jnp.max(lo, axis=-1, keepdims=True)
    on_lo = on_hi & (score.lo == m_lo)
    n = score.hi.shape[-1]
    idx = jnp.min(
        jnp.where(on_lo, jnp.arange(n, dtype=jnp.int32), jnp.int32(n)), axis=-1
    )
    found = jnp.any(ok, axis=-1)
    return jnp.where(found, idx, UNASSIGNED)


@jax.jit
def auction_assign_kernel(
    score: i64.I64,  # [P, N] — larger is better
    eligible: jax.Array,  # bool [P, N]
    capacity: jax.Array,  # int32 [N]
) -> AssignResult:
    """Fixpoint form of :func:`greedy_assign_kernel` — EXACTLY the same
    result, massively fewer sequential steps.

    Iterate: every pod simultaneously picks its best eligible node among
    those where the number of holds by HIGHER-priority (lower-index) pods
    is below capacity (an exclusive cumsum of the one-hot choice matrix
    down the pod axis).  At the fixpoint each pod holds its best node
    given pods 0..p-1's holds — the definition of greedy-in-order.  Pod p
    is provably stable after p rounds (pod 0 after one), and in practice
    rounds ~ contention depth, so the while_loop replaces a P-step scan
    with a handful of [P, N] vector passes."""
    p, n = eligible.shape

    def count_below(choice):
        onehot = jax.nn.one_hot(choice, n, dtype=jnp.int32)  # [-1] -> zeros
        csum = jnp.cumsum(onehot, axis=0)
        return csum - onehot  # exclusive: holds by strictly-lower indices

    def body(state):
        choice, _changed = state
        room = count_below(choice) < capacity[None, :]
        new_choice = _row_lex_argmax(score, eligible & room)
        return new_choice, jnp.any(new_choice != choice)

    def cond(state):
        return state[1]

    init = _row_lex_argmax(score, eligible & (capacity[None, :] > 0))
    choice, _ = jax.lax.while_loop(cond, body, (init, jnp.array(True)))
    taken = jnp.sum(
        jax.nn.one_hot(choice, n, dtype=capacity.dtype), axis=0
    )
    return AssignResult(node_for_pod=choice, capacity_left=capacity - taken)

"""Device-side (JAX/XLA) kernels: the tensorized scheduling core.

The reference's entire mathematical core is ``EvaluateRule`` +
``OrderedList`` (reference telemetry-aware-scheduling/pkg/strategies/core/
operator.go:13-42) executed per pod per node in Go.  Here those become
batched XLA programs over dense ``[metrics, nodes]`` tensors:

- :mod:`ops.i64`     — exact int64 semantics on TPU via (hi i32, lo u32) pairs
- :mod:`ops.rules`   — vectorized rule evaluation / violation masks
- :mod:`ops.scoring` — ordinal Prioritize ranking via multi-key lax.sort
- :mod:`ops.state`   — host mirror: interning tables + dense device tensors
- :mod:`ops.binpack` — GAS per-card first-fit as a vectorized constraint mask
- :mod:`ops.assign`  — batched pods x nodes assignment solve
"""

"""Greedy batch assignment as a single Pallas TPU kernel.

The XLA form (ops/assign.greedy_assign_kernel) is a ``lax.scan`` of P
steps, each a cheap [N] reduction — dominated by per-step overhead.  Here
the whole solve is ONE kernel: a grid over pods streams each pod's score
row HBM -> VMEM while the [N] capacity vector lives in VMEM scratch for
the entire launch (TPU grid steps run sequentially on a core, so scratch
carries the running capacity between steps).  Per step the VPU does the
masked lexicographic argmax and a full-row capacity decrement — no
host round-trips, no per-step dispatch.

Exactness: int64 scores arrive as the (hi: i32, lo: u32) split of
ops/i64.py with ``lo`` pre-biased by 2^31 into an order-preserving i32
(u32 and i32 disagree on ordering; XOR with the sign bit fixes it), so
every compare matches the reference's int64 semantics bit-for-bit.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from platform_aware_scheduling_tpu.ops import i64
from platform_aware_scheduling_tpu.ops.assign import AssignResult

try:  # pallas is TPU/Mosaic; interpret mode covers CPU tests
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False

LANE = 128
NEG_INF_I32 = -(2**31)  # python int: jnp constants may not be captured by kernels


BLOCK_P = 8  # pods per grid step — the minimum i32 sublane tile


def _kernel(score_hi_ref, score_lo_ref, elig_ref, cap_in_ref,
            out_ref, cap_out_ref, cap_ref):
    step = pl.program_id(0)
    n = cap_ref.shape[1]

    @pl.when(step == 0)
    def _init():
        cap_ref[:] = cap_in_ref[:]

    iota = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)

    def row(r, carry):
        cap = cap_ref[0, :]
        ok_row = elig_ref[pl.ds(r, 1), :][0, :]
        hi = score_hi_ref[pl.ds(r, 1), :][0, :]
        lo = score_lo_ref[pl.ds(r, 1), :][0, :]
        ok = (ok_row != 0) & (cap > 0)
        m_hi = jnp.max(jnp.where(ok, hi, jnp.int32(NEG_INF_I32)))
        on_hi = ok & (hi == m_hi)
        m_lo = jnp.max(jnp.where(on_hi, lo, jnp.int32(NEG_INF_I32)))
        on_lo = on_hi & (lo == m_lo)
        chosen = jnp.min(jnp.where(on_lo, iota[0, :], jnp.int32(n)))
        found = chosen < n
        take = (iota[0, :] == chosen) & found
        cap_ref[0, :] = cap - take.astype(jnp.int32)
        out_ref[pl.ds(r, 1), :] = jnp.where(
            found, chosen, jnp.int32(-1)
        ).reshape(1, 1)
        return carry

    jax.lax.fori_loop(0, BLOCK_P, row, 0)

    @pl.when(step == pl.num_programs(0) - 1)
    def _flush():
        cap_out_ref[:] = cap_ref[:]


def _build_call(p: int, n: int, interpret: bool):
    return pl.pallas_call(
        _kernel,
        grid=(p // BLOCK_P,),
        in_specs=[
            pl.BlockSpec((BLOCK_P, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_P, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((BLOCK_P, n), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((BLOCK_P, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((p, 1), jnp.int32),
            jax.ShapeDtypeStruct((1, n), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((1, n), jnp.int32)],
        interpret=interpret,
    )


@partial(jax.jit, static_argnames=("interpret",))
def greedy_assign_pallas(
    score: i64.I64,  # [P, N] — larger is better
    eligible: jax.Array,  # bool [P, N]
    capacity: jax.Array,  # int32 [N]
    interpret: bool = False,
) -> AssignResult:
    """Drop-in replacement for greedy_assign_kernel (identical results)."""
    p, n = eligible.shape
    n_pad = ((n + LANE - 1) // LANE) * LANE
    p_pad = ((p + BLOCK_P - 1) // BLOCK_P) * BLOCK_P
    pad_n = n_pad - n
    pad_p = p_pad - p
    hi = jnp.pad(score.hi, ((0, pad_p), (0, pad_n)))
    # bias u32 -> order-preserving i32 (bit reinterpret, not value convert)
    lo_biased = jax.lax.bitcast_convert_type(
        score.lo ^ jnp.uint32(0x80000000), jnp.int32
    )
    lo = jnp.pad(lo_biased, ((0, pad_p), (0, pad_n)))
    elig = jnp.pad(eligible, ((0, pad_p), (0, pad_n))).astype(jnp.int32)
    cap = jnp.pad(capacity, (0, pad_n)).reshape(1, n_pad).astype(jnp.int32)
    out, cap_left = _build_call(p_pad, n_pad, interpret)(hi, lo, elig, cap)
    return AssignResult(
        node_for_pod=out[:p, 0], capacity_left=cap_left[0, :n]
    )

"""Multi-chip scaling: device meshes + sharded scheduling kernels.

The reference scales only via ``nodeCacheCapable`` and informer caches
(SURVEY §5.7); its cross-process backend is HTTP/JSON + k8s watches
(§2a).  Here the scaling axis of the problem — the cluster node count —
is sharded across a ``jax.sharding.Mesh``: the ``[metrics, nodes]`` state
and the ``[pods, nodes]`` score grid split over the ``nodes`` mesh axis
(pods over ``pods``), with XLA collectives (all_gather / psum over ICI,
DCN across slices) doing what the reference's webhook fan-in cannot —
one fused multi-chip scheduling solve."""

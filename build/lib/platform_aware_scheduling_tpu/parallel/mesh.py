"""Mesh construction + sharding specs for the scheduling tensors.

Axes:
  * ``nodes`` — the cluster-node axis (the problem's scaling dimension;
    the analog of sequence parallelism: candidate sets shard like tokens,
    SURVEY §5.7);
  * ``pods``  — the pending-pod axis (data-parallel-like).

``pad_to_multiple`` keeps shard shapes static per bucket so XLA compiles
once per bucket, not per cluster-size change.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"
POD_AXIS = "pods"


def make_mesh(
    n_node_shards: Optional[int] = None,
    n_pod_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    if n_node_shards is None:
        n_node_shards = len(devices) // n_pod_shards
    grid = np.array(devices[: n_pod_shards * n_node_shards]).reshape(
        n_pod_shards, n_node_shards
    )
    return Mesh(grid, (POD_AXIS, NODE_AXIS))


def make_multislice_mesh(
    n_pod_shards_per_slice: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Multi-slice mesh: the ``pods`` axis spans slices (DCN — the
    infrequent, replicable axis) while ``nodes`` stays inside each slice
    (ICI — where the rank/assign collectives live).  Uses
    ``mesh_utils.create_hybrid_device_mesh`` when slice topology is
    exposed; degenerates to :func:`make_mesh` on a single slice or CPU.
    """
    from jax.experimental import mesh_utils

    from platform_aware_scheduling_tpu.utils import klog

    devices = list(devices if devices is not None else jax.devices())
    slice_ids = {getattr(d, "slice_index", 0) for d in devices}
    n_slices = max(len(slice_ids), 1)
    per_slice = len(devices) // max(n_slices, 1)
    uneven = len(devices) % n_slices != 0
    indivisible = (
        per_slice == 0 or per_slice % max(n_pod_shards_per_slice, 1) != 0
    )
    if n_slices <= 1 or uneven or indivisible:
        if n_slices > 1:
            klog.warning(
                "multi-slice topology (%d slices x %d devices) does not "
                "factor as (%d pods x nodes); using a flat mesh",
                n_slices,
                per_slice,
                n_pod_shards_per_slice,
            )
        return make_mesh(n_pod_shards=n_pod_shards_per_slice, devices=devices)
    grid = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(n_pod_shards_per_slice, per_slice // n_pod_shards_per_slice),
        dcn_mesh_shape=(n_slices, 1),
        devices=devices,
    )
    return Mesh(grid, (POD_AXIS, NODE_AXIS))


def node_sharded(mesh: Mesh) -> NamedSharding:
    """[..., nodes] arrays: shard the trailing axis over ``nodes``."""
    return NamedSharding(mesh, P(None, NODE_AXIS))


def grid_sharded(mesh: Mesh) -> NamedSharding:
    """[pods, nodes] arrays: shard both axes."""
    return NamedSharding(mesh, P(POD_AXIS, NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(arr: np.ndarray, axis: int, multiple: int, fill=0) -> np.ndarray:
    size = arr.shape[axis]
    target = ((size + multiple - 1) // multiple) * multiple
    if target == size:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, target - size)
    return np.pad(arr, pad, constant_values=fill)

"""Scheduling models: compositions of the ops/ kernels into full solves.

``batch_scheduler`` is the flagship — the framework's "training step":
one XLA program taking cluster state + the entire pending-pod set and
producing a capacity-feasible assignment (filter -> score -> assign)."""

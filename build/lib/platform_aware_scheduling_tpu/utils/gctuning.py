"""Latency-service GC posture for the extender processes.

Request handling allocates bulk bytes (parsed bodies, response buffers)
but creates no reference cycles, so CPython's default generational
thresholds only add tail latency: every young-gen collection scans a
JAX-sized module graph for garbage that is reclaimed by refcounting
anyway.  The standard tuning for this shape of service — freeze the
warmed startup heap out of collection and raise the gen-0 threshold — is
applied once, after assembly, before serving.

Opt out with ``PAS_TPU_NO_GC_TUNING=1`` (e.g. when embedding the
extender in a host application that owns GC policy).
"""

from __future__ import annotations

import gc
import os

from platform_aware_scheduling_tpu.utils import klog


def tune_for_serving() -> bool:
    """Apply the serving GC posture; returns whether it was applied."""
    if os.environ.get("PAS_TPU_NO_GC_TUNING") == "1":
        return False
    gc.collect()
    gc.freeze()
    gc.set_threshold(100_000, 50, 50)
    klog.v(2).info_s(
        "GC tuned for serving (startup heap frozen, gen0 threshold 100k)",
        component="extender",
    )
    return True

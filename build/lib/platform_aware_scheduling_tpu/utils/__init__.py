"""Shared host-side utilities: logging, quantities, durations."""

from platform_aware_scheduling_tpu.utils.quantity import Quantity
from platform_aware_scheduling_tpu.utils.duration import parse_duration

__all__ = ["Quantity", "parse_duration"]

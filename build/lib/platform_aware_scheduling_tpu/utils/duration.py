"""Go-style duration parsing (``time.ParseDuration`` equivalent).

The reference parses its ``--syncPeriod`` flag with ``time.ParseDuration``
(reference telemetry-aware-scheduling/cmd/main.go:66-70); this reproduces the
accepted grammar: a signed sequence of decimal numbers with optional fraction
and a unit suffix from ns/us/µs/ms/s/m/h, e.g. "2s", "1.5h", "300ms".
Returns seconds as a float.
"""

from __future__ import annotations

import re

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "μs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_PART_RE = re.compile(r"([0-9]*\.?[0-9]+)(ns|us|µs|μs|ms|s|m|h)")


class DurationParseError(ValueError):
    pass


def parse_duration(text: str) -> float:
    s = text.strip()
    if not s:
        raise DurationParseError("empty duration")
    sign = 1.0
    if s[0] in "+-":
        if s[0] == "-":
            sign = -1.0
        s = s[1:]
    if s == "0":
        return 0.0
    total = 0.0
    pos = 0
    for m in _PART_RE.finditer(s):
        if m.start() != pos:
            raise DurationParseError(f"invalid duration: {text!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise DurationParseError(f"invalid duration: {text!r}")
    return sign * total

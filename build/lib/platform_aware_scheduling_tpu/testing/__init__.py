"""Test doubles: in-memory fake kube API, fake metrics backends, builders.

The functional equivalent of the reference's fixture inventory (survey §4):
client-go ``fake.NewSimpleClientset`` -> :class:`FakeKubeClient`;
``metrics.DummyMetricsClient`` -> :class:`DummyMetricsClient`;
mock caches/strategies live next to the code they fake.
"""

from platform_aware_scheduling_tpu.testing.fake_kube import FakeKubeClient
from platform_aware_scheduling_tpu.testing.builders import make_node, make_pod, make_policy

__all__ = ["FakeKubeClient", "make_node", "make_pod", "make_policy"]

"""Builders for k8s object dicts used across tests and benchmarks."""

from __future__ import annotations

from typing import Dict, List, Optional

from platform_aware_scheduling_tpu.kube.objects import Node, Pod


def make_node(
    name: str,
    labels: Optional[Dict[str, str]] = None,
    allocatable: Optional[Dict[str, str]] = None,
) -> Node:
    return Node(
        {
            "metadata": {"name": name, "labels": labels or {}},
            "status": {"allocatable": allocatable or {}},
        }
    )


def make_pod(
    name: str,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    annotations: Optional[Dict[str, str]] = None,
    container_requests: Optional[List[Dict[str, str]]] = None,
    node_name: str = "",
    phase: str = "Pending",
    uid: str = "",
) -> Pod:
    containers = [
        {"name": f"c{i}", "resources": {"requests": dict(reqs)}}
        for i, reqs in enumerate(container_requests or [{}])
    ]
    raw = {
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": labels or {},
            "uid": uid or f"uid-{namespace}-{name}",
        },
        "spec": {"containers": containers},
        "status": {"phase": phase},
    }
    if annotations:
        raw["metadata"]["annotations"] = dict(annotations)
    if node_name:
        raw["spec"]["nodeName"] = node_name
    return Pod(raw)


def make_policy(
    name: str,
    namespace: str = "default",
    strategies: Optional[Dict[str, List[Dict]]] = None,
) -> Dict:
    """Build a TASPolicy dict.  ``strategies`` maps strategy type ->
    list of (metricname, operator, target) rule dicts."""
    return {
        "apiVersion": "telemetry.intel.com/v1alpha1",
        "kind": "TASPolicy",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "strategies": {
                stype: {"policyName": name, "rules": list(rules)}
                for stype, rules in (strategies or {}).items()
            }
        },
    }


def rule(metricname: str, operator: str, target: int) -> Dict:
    return {"metricname": metricname, "operator": operator, "target": target}

"""In-cluster validation runner: the service as a coverage-instrumented
process with a /prestop hook.

Capability parity with the reference's validation build
(reference gpu-aware-scheduling/pkg/gpuscheduler/validation_test.go:1-68):
the Go version wraps main() in a test binary so it can run *in a real
cluster with coverage instrumentation*, terminated via an HTTP prestop
hook on port 8088 that lets the coverage profile flush.

Python equivalent::

    coverage run -m platform_aware_scheduling_tpu.testing.validation tas \
        --unsafe --port 9001

A container preStop hook (or operator) then calls
``GET http://localhost:8088/prestop``; the runner shuts the service down
cleanly so ``coverage`` writes its data file.
"""

from __future__ import annotations

import sys
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

PRESTOP_PORT = 8088


def serve_prestop(trigger: threading.Event, port: int = PRESTOP_PORT) -> HTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/prestop":
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"stopping\n")
                trigger.set()
            else:
                self.send_response(404)
                self.end_headers()

        do_POST = do_GET

        def log_message(self, fmt, *args):
            pass

    server = HTTPServer(("127.0.0.1", port), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("tas", "gas"):
        print("usage: validation {tas|gas} [service flags...]", file=sys.stderr)
        return 2
    which, rest = argv[0], argv[1:]

    import signal

    stop = threading.Event()
    prestop = serve_prestop(stop)

    if which == "tas":
        from platform_aware_scheduling_tpu.cmd import tas as svc
    else:
        from platform_aware_scheduling_tpu.cmd import gas as svc

    result = [0]
    thread = threading.Thread(
        target=lambda: result.__setitem__(0, svc.main(rest)), daemon=True
    )
    thread.start()
    stop.wait()
    # deliver the service's own shutdown path (it waits on SIGINT/SIGTERM)
    signal.raise_signal(signal.SIGTERM)
    thread.join(timeout=10)
    prestop.shutdown()
    return result[0]


if __name__ == "__main__":
    raise SystemExit(main())

"""Pre-seeded test doubles.

Functional parity with the reference's hand-written fakes (SURVEY §4
fixtures inventory): ``mock_self_updating_cache`` mirrors
``cache.MockSelfUpdatingCache`` (reference pkg/cache/mocks.go:16-39 — a
live cache pre-seeded with dummy metrics), ``dummy_metrics_client`` mirrors
``metrics.DummyMetricsClient`` + ``InstanceOfMockMetricClientMap``
(pkg/metrics/mocks.go:40-75), ``test_node_metric_custom_info`` mirrors
``TestNodeMetricCustomInfo``, and ``MockStrategy`` mirrors
``core.MockStrategy`` (pkg/strategies/core/mocks.go).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

from platform_aware_scheduling_tpu.tas.cache import AutoUpdatingCache
from platform_aware_scheduling_tpu.tas.metrics import (
    DummyMetricsClient,
    NodeMetric,
    NodeMetricsInfo,
)
from platform_aware_scheduling_tpu.utils.quantity import Quantity


def test_node_metric_custom_info(
    node_names: Sequence[str], values: Sequence[int]
) -> NodeMetricsInfo:
    """Canned per-node metric vectors (reference metrics/mocks.go)."""
    return {
        name: NodeMetric(value=Quantity(str(value)))
        for name, value in zip(node_names, values)
    }


def instance_of_mock_metric_client_map() -> Dict[str, NodeMetricsInfo]:
    return {
        "dummyMetric1": test_node_metric_custom_info(["node A", "node B"], [1, 2]),
        "dummyMetric2": test_node_metric_custom_info(["node A", "node B"], [3, 4]),
        "dummyMetric3": test_node_metric_custom_info(["node A", "node B"], [5, 6]),
    }


def dummy_metrics_client() -> DummyMetricsClient:
    return DummyMetricsClient(instance_of_mock_metric_client_map())


def mock_self_updating_cache() -> AutoUpdatingCache:
    """A live cache pre-seeded with the dummy metrics
    (reference cache/mocks.go MockSelfUpdatingCache)."""
    cache = AutoUpdatingCache()
    for name, info in instance_of_mock_metric_client_map().items():
        cache.write_metric(name, info)
    return cache


def mock_empty_self_updating_cache() -> AutoUpdatingCache:
    """(reference cache/mocks.go MockEmptySelfUpdatingCache)"""
    return AutoUpdatingCache()


class MockStrategy:
    """Registry/enforcer test double (reference core/mocks.go)."""

    def __init__(self, strategy_type: str = "mock", policy_name: str = "mock"):
        self._type = strategy_type
        self.policy_name = policy_name
        self.rules: List = []
        self.enforce_calls = 0
        self.cleanup_calls = 0

    def violated(self, cache) -> Dict[str, None]:
        return {}

    def strategy_type(self) -> str:
        return self._type

    def equals(self, other) -> bool:
        return (
            isinstance(other, MockStrategy)
            and other._type == self._type
            and other.policy_name == self.policy_name
        )

    def get_policy_name(self) -> str:
        return self.policy_name

    def set_policy_name(self, name: str) -> None:
        self.policy_name = name

    def enforce(self, enforcer, cache) -> int:
        self.enforce_calls += 1
        return 0

    def cleanup(self, enforcer, policy_name: str) -> None:
        self.cleanup_calls += 1

"""Scheduler-extender wire layer: protocol types, HTTP(S) server, middleware.

The north-facing protocol of the framework — kube-scheduler POSTs JSON to
``/scheduler/{filter,prioritize,bind}`` — is kept wire-compatible with the
reference (reference extender/scheduler.go:86-91, extender/types.go:26-82).
"""

from platform_aware_scheduling_tpu.extender.types import (
    Args,
    BindingArgs,
    BindingResult,
    FilterResult,
    HostPriority,
    Scheduler,
)
from platform_aware_scheduling_tpu.extender.server import (
    HTTPRequest,
    HTTPResponse,
    Server,
)

__all__ = [
    "Args",
    "BindingArgs",
    "BindingResult",
    "FilterResult",
    "HostPriority",
    "Scheduler",
    "Server",
    "HTTPRequest",
    "HTTPResponse",
]

"""platform_aware_scheduling_tpu — a TPU-native platform-aware scheduling framework.

A brand-new implementation of the capabilities of
intel/platform-aware-scheduling (reference at /root/reference): Kubernetes
scheduler extenders that filter / prioritize / bind pods on live platform
telemetry (TAS) and per-GPU-card resource bin-packing (GAS).

Instead of the reference's per-pod, per-node Go loops, the scoring and
placement core here is a batched JAX/XLA program: rule evaluation, ranking,
and per-card feasibility are computed over dense (pods x nodes x metrics)
tensors in one compiled pass (see ``ops/`` and ``models/``), sharded over a
``jax.sharding.Mesh`` for large clusters (see ``parallel/``). The host-side
subsystems (HTTP extender protocol, policy CRD controller, caches, informers)
live in ``extender/``, ``tas/``, ``gas/``, and ``kube/``.
"""

__version__ = "0.1.0"

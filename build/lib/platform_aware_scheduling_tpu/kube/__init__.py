"""Minimal Kubernetes client layer: dict-backed objects, REST client,
informers, workqueues, and an in-memory fake API server for tests.

This is the client-go equivalent of the framework.  Objects stay as their
wire-format JSON dicts (wrapped for ergonomic access) so requests can be
re-serialized bit-for-bit; the tensorized scheduling core never sees these —
it sees the dense mirrors built in ``models/state.py``.
"""

from platform_aware_scheduling_tpu.kube.objects import Node, Pod, object_key

__all__ = ["Node", "Pod", "object_key"]

"""TASPolicy CRD schema, v1alpha1.

Mirrors the reference CRD (reference telemetry-aware-scheduling/pkg/
telemetrypolicy/api/v1alpha1/types.go:9-45): group ``telemetry.intel.com``,
version ``v1alpha1``, plural ``taspolicies``.  ``spec.strategies`` maps a
strategy-type name (scheduleonmetric / dontschedule / deschedule / labeling)
to a ``TASPolicyStrategy`` whose rules are ``{metricname, operator, target}``.
JSON uses the same lowercase field names as the reference's struct tags.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

GROUP = "telemetry.intel.com"
VERSION = "v1alpha1"
PLURAL = "taspolicies"
API_VERSION = f"{GROUP}/{VERSION}"
KIND = "TASPolicy"


@dataclass(frozen=True)
class TASPolicyRule:
    """One rule: a metric name, an operator (LessThan/GreaterThan/Equals) and
    an int64 target (reference types.go:31-36)."""

    metricname: str
    operator: str
    target: int

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "TASPolicyRule":
        return cls(
            metricname=obj.get("metricname", ""),
            operator=obj.get("operator", ""),
            target=int(obj.get("target", 0)),
        )

    def to_obj(self) -> Dict[str, Any]:
        return {
            "metricname": self.metricname,
            "operator": self.operator,
            "target": self.target,
        }


@dataclass
class TASPolicyStrategy:
    """A named set of rules (reference types.go:25-29)."""

    policy_name: str = ""
    rules: List[TASPolicyRule] = field(default_factory=list)

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "TASPolicyStrategy":
        return cls(
            policy_name=obj.get("policyName", ""),
            rules=[TASPolicyRule.from_obj(r) for r in obj.get("rules") or []],
        )

    def to_obj(self) -> Dict[str, Any]:
        return {
            "policyName": self.policy_name,
            "rules": [r.to_obj() for r in self.rules],
        }


@dataclass
class TASPolicy:
    """The policy object (reference types.go:16-23).  ``metadata`` is kept as
    the raw dict; ``strategies`` maps strategy type -> TASPolicyStrategy."""

    metadata: Dict[str, Any] = field(default_factory=dict)
    strategies: Dict[str, TASPolicyStrategy] = field(default_factory=dict)
    status: Dict[str, Any] = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @property
    def namespace(self) -> str:
        return self.metadata.get("namespace", "")

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "TASPolicy":
        spec = obj.get("spec") or {}
        strategies = {
            name: TASPolicyStrategy.from_obj(strat)
            for name, strat in (spec.get("strategies") or {}).items()
        }
        return cls(
            metadata=copy.deepcopy(obj.get("metadata") or {}),
            strategies=strategies,
            status=copy.deepcopy(obj.get("status") or {}),
        )

    def to_obj(self) -> Dict[str, Any]:
        return {
            "apiVersion": API_VERSION,
            "kind": KIND,
            "metadata": copy.deepcopy(self.metadata),
            "spec": {
                "strategies": {
                    name: strat.to_obj() for name, strat in self.strategies.items()
                }
            },
            "status": copy.deepcopy(self.status),
        }

    def deep_copy(self) -> "TASPolicy":
        return TASPolicy.from_obj(self.to_obj())

"""TASPolicy CRD: types and REST client (group telemetry.intel.com/v1alpha1)."""

from platform_aware_scheduling_tpu.tas.policy.v1alpha1 import (
    GROUP,
    PLURAL,
    VERSION,
    TASPolicy,
    TASPolicyRule,
    TASPolicyStrategy,
)

__all__ = [
    "TASPolicy",
    "TASPolicyRule",
    "TASPolicyStrategy",
    "GROUP",
    "VERSION",
    "PLURAL",
]

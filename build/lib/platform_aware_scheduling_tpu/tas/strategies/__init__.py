"""TAS policy strategies: scheduleonmetric, dontschedule, deschedule
(reference telemetry-aware-scheduling/pkg/strategies/)."""

"""Telemetry-Aware Scheduling (TAS): policy-driven filter/prioritize/deschedule
on live platform telemetry from the custom-metrics API.

Reference module: telemetry-aware-scheduling/ (survey §1 L2-L6).  The scoring
hot loop is replaced by the batched JAX path in ``models/tas_model.py``.
"""

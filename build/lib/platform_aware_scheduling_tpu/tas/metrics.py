"""Node-metric ingestion from the custom-metrics API.

Reference: telemetry-aware-scheduling/pkg/metrics/client.go.  ``NodeMetric``
carries timestamp / window / value (client.go:25-32); ``get_node_metric``
queries root-scoped Node metrics with empty selectors (client.go:51-61) and
``wrap_metrics`` converts the MetricValueList with a default 60 s window
(client.go:64-78).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Protocol

from platform_aware_scheduling_tpu.utils.quantity import Quantity


@dataclass
class NodeMetric:
    """One piece of telemetry for one node."""

    value: Quantity
    timestamp: str = ""
    window_seconds: float = 60.0


# node name -> NodeMetric (reference client.go:34-35)
NodeMetricsInfo = Dict[str, NodeMetric]


class MetricsError(Exception):
    pass


class Client(Protocol):
    """Knows how to fetch one named metric for every node
    (reference client.go:20-22)."""

    def get_node_metric(self, metric_name: str) -> NodeMetricsInfo: ...


def wrap_metrics(metric_value_list: Dict[str, Any]) -> NodeMetricsInfo:
    """MetricValueList -> NodeMetricsInfo (reference client.go:64-78);
    default window one minute when windowSeconds is absent."""
    result: NodeMetricsInfo = {}
    for item in metric_value_list.get("items") or []:
        window = item.get("windowSeconds")
        result[(item.get("describedObject") or {}).get("name", "")] = NodeMetric(
            value=Quantity(str(item.get("value", "0"))),
            timestamp=item.get("timestamp", ""),
            window_seconds=float(window) if window is not None else 60.0,
        )
    return result


class CustomMetricsClient:
    """Live client over the kube custom-metrics API
    (reference client.go:38-61)."""

    def __init__(self, kube_client):
        self._kube = kube_client

    def get_node_metric(self, metric_name: str) -> NodeMetricsInfo:
        try:
            value_list = self._kube.get_node_custom_metric(metric_name)
        except Exception as exc:
            raise MetricsError(
                "unable to fetch metrics from custom metrics API: " + str(exc)
            ) from exc
        if not (value_list.get("items") or []):
            raise MetricsError("no metrics returned from custom metrics API")
        return wrap_metrics(value_list)


class DummyMetricsClient:
    """Canned metrics client (the reference's test fake,
    pkg/metrics/mocks.go:40-75)."""

    def __init__(self, store: Dict[str, NodeMetricsInfo] | None = None):
        self.store: Dict[str, NodeMetricsInfo] = store if store is not None else {}

    def get_node_metric(self, metric_name: str) -> NodeMetricsInfo:
        if metric_name not in self.store:
            raise MetricsError(f"no metric {metric_name} found")
        return dict(self.store[metric_name])


def instance_of_mock_metric_client_map(
    metric_name: str = "dummyMetric1",
) -> Dict[str, NodeMetricsInfo]:
    """Pre-seeded per-node metric vectors in the spirit of the reference's
    ``InstanceOfMockMetricClientMap`` / ``TestNodeMetricCustomInfo``."""
    return {
        metric_name: {
            "node A": NodeMetric(value=Quantity("100")),
            "node B": NodeMetric(value=Quantity("200")),
        }
    }

"""Service entry points (the reference's cmd/ binaries):
``cmd.tas`` — telemetry-aware scheduling extender,
``cmd.gas`` — GPU-aware scheduling extender."""
